"""Topology-elastic recovery (ISSUE 7): survive device loss by shrinking.

Three layers, asserted hermetically on the 8-virtual-device CPU rig:

- **Device health + blacklist units**: the process-wide condemn/clear
  lifecycle (with its ``mesh.devices_lost`` counter and
  ``mesh.device_blacklist`` info label), the real put/fetch probe on a
  healthy device, and ``largest_mesh_shape``'s reshard arithmetic —
  word-aligned shapes preferred (the ``packed_halo.supports`` gate),
  any dividing factorisation accepted, (1,1) always reachable.
- **The elastic chaos rows**: a persistent ``device_down`` fault defeats
  the same-tier and forced-ppermute rungs, then the elastic rung probes,
  condemns, and rebuilds on the largest healthy mesh — the supervised
  run completes bit-identical to the fault-free oracle on the SHRUNKEN
  mesh, with the blacklist + ``mesh_shrink`` in the flight ring and
  ``supervisor.restarts``/``mesh.devices_lost`` in the MetricsReport.
  With the supervisor off the behaviour is byte-for-byte the PR-2
  sentinel abort; with EVERY device condemned the ladder degrades to the
  sentinel abort with the full probe results in the flight record.
- **Peer heartbeat units**: two in-process :class:`PeerHeartbeat`
  monitors with injected addresses prove liveness tracking and the
  bounded dead-peer detection (the cross-process SIGKILL integration is
  ``tests/multihost_worker.py::peerloss_main``).

Chaos rows are marked ``chaos`` like the rest of the matrix.
"""

import queue
import time

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import DispatchError
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.engine.supervisor import (
    AllDevicesCondemned,
    Supervisor,
    supervise,
)
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.parallel import mesh as mesh_lib
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)


@pytest.fixture(autouse=True)
def clean_blacklist():
    """The blacklist is deliberately process-wide (condemned silicon stays
    condemned for every later run) — tests must not leak it."""
    mesh_lib.clear_blacklist()
    yield
    mesh_lib.clear_blacklist()


# -- device health + blacklist units -------------------------------------------


def test_condemn_blacklist_lifecycle_and_metrics():
    import jax

    from distributed_gol_tpu.obs import metrics as metrics_lib

    before = metrics_lib.REGISTRY.counter("mesh.devices_lost").value
    assert mesh_lib.blacklisted() == frozenset()
    assert mesh_lib.condemn([3, 5]) == [3, 5]
    assert mesh_lib.condemn([5, jax.devices()[1]]) == [jax.devices()[1].id]
    assert mesh_lib.blacklisted() == frozenset({1, 3, 5})
    # Counter counts NEWLY condemned only; the label is the full list.
    assert metrics_lib.REGISTRY.counter("mesh.devices_lost").value - before == 3
    snap = metrics_lib.REGISTRY.snapshot().to_dict()
    assert snap["info"]["mesh.device_blacklist"] == "1,3,5"
    # healthy_devices filters; lost_device_count counts real devices only
    # (ids 3 and 5 may or may not exist on this rig, id 1 does).
    healthy = mesh_lib.healthy_devices()
    assert all(d.id not in (1, 3, 5) for d in healthy)
    assert mesh_lib.lost_device_count() >= 1
    frac = mesh_lib.capacity_fraction()
    assert 0.0 < frac < 1.0
    mesh_lib.clear_blacklist()
    assert mesh_lib.blacklisted() == frozenset()
    assert mesh_lib.capacity_fraction() == 1.0
    snap = metrics_lib.REGISTRY.snapshot().to_dict()
    assert snap["info"]["mesh.device_blacklist"] == ""


def test_probe_classifies_real_devices_healthy():
    """The real put/fetch probe on this rig's (healthy) CPU devices."""
    import jax

    healthy, condemned = mesh_lib.probe_devices(jax.devices()[:2])
    assert [d.id for d in healthy] == [0, 1] and condemned == []


def test_make_mesh_default_skips_blacklisted_devices():
    import jax

    mesh_lib.condemn([0])
    mesh = mesh_lib.make_mesh((2, 1))
    ids = [d.id for d in mesh.devices.flat]
    assert 0 not in ids and ids == [1, 2]
    # An explicit device list still wins (callers own their topology).
    explicit = mesh_lib.make_mesh((2, 1), jax.devices()[:2])
    assert [d.id for d in explicit.devices.flat] == [0, 1]
    # Too few survivors: the error names the blacklist.
    mesh_lib.condemn(range(1, len(jax.devices())))
    with pytest.raises(ValueError, match="blacklisted"):
        mesh_lib.make_mesh((2, 1))


@pytest.mark.parametrize(
    "n,h,w,want",
    [
        (8, 512, 512, (2, 4)),  # full health: most devices, squarest
        (7, 64, 64, (2, 2)),    # 7 and 6,5 don't divide 64; 4 does
        (3, 512, 512, (1, 2)),  # 3 doesn't divide 512; 2 does
        (1, 512, 512, (1, 1)),  # the universal fallback
        (4, 64, 64, (2, 2)),    # w//nx = 32: word-aligned 2-D form
        (8, 64, 64, (4, 2)),    # (2,4) loses word alignment (16 cols); (4,2) keeps it
    ],
)
def test_largest_mesh_shape_prefers_word_aligned(n, h, w, want):
    assert mesh_lib.largest_mesh_shape(n, h, w) == want


def test_largest_mesh_shape_falls_back_past_word_alignment():
    """A board too narrow for any word-aligned multi-device split still
    shrinks onto a dividing factorisation (the roll engine's territory)
    rather than failing — and (1,1) is always reachable."""
    assert mesh_lib.largest_mesh_shape(4, 8, 8) == (2, 2)  # 4 cols/device
    assert mesh_lib.largest_mesh_shape(4, 8, 8, word_aligned=False) == (2, 2)
    assert mesh_lib.largest_mesh_shape(5, 7, 13) == (1, 1)
    with pytest.raises(ValueError):
        mesh_lib.largest_mesh_shape(0, 64, 64)


def test_backend_single_device_sidesteps_blacklisted_default():
    """A (1,1) backend whose default device was condemned must genuinely
    move off it — and record the device it landed on."""
    import jax

    params = gol.Params(
        turns=4, image_width=16, image_height=16, engine="roll",
        soup_density=0.25, soup_seed=11, ticker_period=60.0,
    )
    assert Backend(params).devices == [jax.devices()[0]]
    mesh_lib.condemn([0])
    assert Backend(params).devices == [jax.devices()[1]]
    # Explicit placement pins regardless of the blacklist default.
    pinned = Backend(params, devices=[jax.devices()[2]])
    assert pinned.devices == [jax.devices()[2]]
    mesh_lib.condemn(range(len(jax.devices())))
    with pytest.raises(ValueError, match="blacklisted"):
        Backend(params)


# -- the elastic chaos rows ----------------------------------------------------

# The sharded row the acceptance criterion names: 6 dispatches of 5 turns
# on an (8,1) packed mesh; device 7 dies persistently at dispatch 2.  The
# largest healthy mesh over the 7 survivors that keeps 64/nx word-aligned
# is (2,2) — the shrink crosses mesh DIMENSIONALITY, not just size.
SHARDED = dict(
    engine="packed", mesh_shape=(8, 1), image_width=64, image_height=64,
    superstep=5, turns=30,
)


def elastic_params(out_dir, **kw):
    cfg = dict(SHARDED)
    cfg.update(
        soup_density=0.25, soup_seed=11, out_dir=out_dir, cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return gol.Params(**cfg)


def drain(events):
    out = []
    while (e := events.get(timeout=60)) is not None:
        out.append(e)
    return out


def persistent_harness(params, plan):
    """ONE FaultInjectionBackend across every supervisor attempt (the
    rebind seam): device_down stays down however the ladder rebuilds.
    Returns (harness, backend_factory)."""
    harness = FaultInjectionBackend(Backend(params), plan)

    def factory(p, attempt):
        return harness if attempt == 0 else harness.rebind(Backend(p))

    return harness, factory


@pytest.fixture(scope="module")
def sharded_oracle(tmp_path_factory):
    out = tmp_path_factory.mktemp("elastic-oracle")
    p = elastic_params(out)
    events: queue.Queue = queue.Queue()
    gol.run(p, events)
    stream = drain(events)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    return final, (out / f"{p.final_output_name}.pgm").read_bytes()


@pytest.mark.chaos
def test_device_down_recovers_on_shrunken_mesh(tmp_path, sharded_oracle):
    """THE acceptance row: a persistent device_down on a sharded run
    defeats the same-tier and forced rungs (every rebuild still computes
    on the dead device), then the elastic rung condemns it and rebuilds
    on the largest healthy mesh — (8,1) -> (2,2) — restoring the
    checkpoint resharded, and the run completes bit-identical to the
    fault-free full-mesh oracle.  A recovered run writes no flight FILE;
    the blacklist + shrink live in the supervisor's ring and the restart
    history, and the counters ride the terminal MetricsReport."""
    s = SHARDED["superstep"]
    params = elastic_params(
        tmp_path, checkpoint_every_turns=s, restart_limit=3
    )
    plan = FaultPlan([Fault(2, "device_down", device=7)])
    harness, factory = persistent_harness(params, plan)
    events: queue.Queue = queue.Queue()
    session = Session()
    sup = supervise(
        params,
        events,
        session=session,
        backend_factory=factory,
        device_probe=harness.device_probe,
    )
    stream = drain(events)

    # Bit-identical to the fault-free (8,1) oracle, on a (2,2) mesh.
    want_final, want_board = sharded_oracle
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert sorted(final.alive) == sorted(want_final.alive)
    got = (tmp_path / f"{params.final_output_name}.pgm").read_bytes()
    assert got == want_board, "recovered run differs from fault-free oracle"

    # The ladder: two full-topology attempts failed, the third shrank.
    assert [r["attempt"] for r in sup.history] == [1, 2, 3]
    assert [r["tier"] for r in sup.history] == ["factory", "factory", "elastic"]
    assert sup.history[0]["mesh_shape"] == [8, 1]
    assert sup.history[2]["mesh_shape"] == [2, 2]
    assert sup.history[2]["excluded_devices"] == [7]
    assert mesh_lib.blacklisted() == frozenset({7})

    # Blacklist + shrink visible in the (shared) flight ring...
    kinds = [r["kind"] for r in sup.flight.records()]
    assert "device_blacklist" in kinds and "mesh_shrink" in kinds
    shrink = [r for r in sup.flight.records() if r["kind"] == "mesh_shrink"][0]
    assert shrink["from_shape"] == [8, 1] and shrink["to_shape"] == [2, 2]
    # ...but a RECOVERED run leaves no postmortem file.
    assert flight_lib.latest_flight_record(tmp_path) is None

    # And in the run's own telemetry.
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["supervisor.restarts"] == 3
    assert counters["mesh.devices_lost"] == 1
    assert report.snapshot["info"]["mesh.device_blacklist"] == "7"
    # Nothing left parked: the recovered run consumed its rollback state.
    assert session.check_states(params.image_width, params.image_height) is None


@pytest.mark.chaos
def test_device_down_unsupervised_is_pr2_sentinel_abort(tmp_path, sharded_oracle):
    """With the supervisor OFF (restart_limit=0, the default), a
    device_down is byte-for-byte the PR-2 contract: retry announced,
    terminal abort with the sentinel, last good board parked resumable,
    flight record explaining the cause — no probe, no blacklist."""
    params = elastic_params(tmp_path / "faulted")
    (tmp_path / "faulted").mkdir()
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(2, "device_down", device=7)])
    )
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError, match="device_down"):
        gol.run(params, events, session=session, backend=backend)
    stream = drain(events)  # sentinel guaranteed on the abort path
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[-1].checkpointed
    path = flight_lib.latest_flight_record(tmp_path / "faulted")
    assert path is not None
    doc = flight_lib.load_flight_record(path)
    assert doc["cause"] == "RuntimeError"
    assert doc["records"][-1]["kind"] == "abort"
    # Unsupervised: the elastic machinery never engaged.
    kinds = {r["kind"] for r in doc["records"]}
    assert "device_blacklist" not in kinds and "mesh_shrink" not in kinds
    assert mesh_lib.blacklisted() == frozenset()
    ckpt = session.check_states(params.image_width, params.image_height)
    assert ckpt is not None and 0 < ckpt.turn < params.turns


@pytest.mark.chaos
def test_device_down_on_2d_mesh_recovers_on_shrunk_2d_mesh(tmp_path):
    """Round-7 elastic row (ISSUE 13): a persistent device_down on a
    (2, 4) 2-D mesh running the pallas-packed 2-D tile tier.  The
    elastic rung condemns the dead chip and ``largest_mesh_shape(7, 64,
    128)`` lands on (2, 2) — a 2-D → 2-D shrink that keeps the
    word-aligned fast tier (128/2 = 64 cells/device, % 32 == 0) — and
    the resharded run completes bit-identical to a fault-free (2, 4)
    oracle."""
    cfg = dict(
        engine="pallas-packed", mesh_shape=(2, 4),
        image_width=128, image_height=64, superstep=5, turns=30,
        soup_density=0.25, soup_seed=11, cycle_check=0, ticker_period=60.0,
    )
    oracle_dir = tmp_path / "oracle"
    oracle_dir.mkdir()
    p0 = gol.Params(**cfg, out_dir=oracle_dir)
    events0: queue.Queue = queue.Queue()
    gol.run(p0, events0)
    want_final = [
        e for e in drain(events0) if isinstance(e, gol.FinalTurnComplete)
    ][0]
    want_board = (oracle_dir / f"{p0.final_output_name}.pgm").read_bytes()

    params = gol.Params(
        **cfg, out_dir=tmp_path, checkpoint_every_turns=5, restart_limit=3
    )
    plan = FaultPlan([Fault(2, "device_down", device=7)])
    harness, factory = persistent_harness(params, plan)
    events: queue.Queue = queue.Queue()
    session = Session()
    sup = supervise(
        params,
        events,
        session=session,
        backend_factory=factory,
        device_probe=harness.device_probe,
    )
    stream = drain(events)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert sorted(final.alive) == sorted(want_final.alive)
    got = (tmp_path / f"{params.final_output_name}.pgm").read_bytes()
    assert got == want_board, "2-D recovered run differs from 2-D oracle"
    assert sup.history[-1]["tier"] == "elastic"
    assert sup.history[-1]["mesh_shape"] == [2, 2]
    assert sup.history[-1]["excluded_devices"] == [7]
    shrink = [r for r in sup.flight.records() if r["kind"] == "mesh_shrink"][0]
    assert shrink["from_shape"] == [2, 4] and shrink["to_shape"] == [2, 2]


@pytest.mark.chaos
def test_all_devices_condemned_degrades_to_clean_abort(tmp_path):
    """The unsalvageable topology: devices die one per dispatch (distinct
    fault indices — a plan schedules one fault per dispatch) until every
    device on the rig is down.  The elastic rung recovers once onto a
    surviving device, then the NEXT probe condemns the remainder and the
    ladder degrades to PR 2's sentinel abort — with the full probe
    results (the ``device_blacklist`` rows), the ``elastic_exhausted``
    marker, and the blacklist on the ``supervisor_exhausted`` tail all
    in the dumped flight record.  The restart budget is NOT the binding
    constraint (limit 5, only 3 spent): the topology is."""
    import jax

    params = gol.Params(
        engine="roll", mesh_shape=(1, 1), image_width=16, image_height=16,
        superstep=4, turns=24, soup_density=0.25, soup_seed=11,
        out_dir=tmp_path / "faulted", cycle_check=0, ticker_period=60.0,
        checkpoint_every_turns=4, restart_limit=5,
    )
    (tmp_path / "faulted").mkdir()
    n = len(jax.devices())
    plan = FaultPlan(
        [Fault(2 + d, "device_down", device=d) for d in range(n)]
    )
    harness, factory = persistent_harness(params, plan)
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError, match="device_down"):
        supervise(
            params,
            events,
            backend_factory=factory,
            device_probe=harness.device_probe,
        )
    drain(events)  # sentinel still guaranteed
    path = flight_lib.latest_flight_record(tmp_path / "faulted")
    assert path is not None
    doc = flight_lib.load_flight_record(path)
    records = doc["records"]
    probe_rows = [r for r in records if r["kind"] == "device_blacklist"]
    # Two elastic probes ran: the first condemned the devices dead so
    # far, the last found the whole rig condemned.
    assert len(probe_rows) >= 2
    assert probe_rows[-1]["blacklist"] == list(range(n))
    assert "elastic_exhausted" in {r["kind"] for r in records}
    tail_sup = [r for r in records if r["kind"] == "supervisor_exhausted"][0]
    assert tail_sup["device_blacklist"] == list(range(n))
    assert tail_sup["restarts"] == 3  # the topology ended it, not the budget
    assert mesh_lib.blacklisted() == frozenset(range(n))

    # The dumped record renders with the dedicated prose rows (the
    # pinning half of the flight-report satellite, on a REAL record).
    from tools.flight_report import render

    text = render(doc, tail=200)
    assert "elastic probe (attempt 3)" in text
    assert "condemned device(s) [0, 1, 2, 3, 4, 5]" in text
    assert "elastic rung EXHAUSTED" in text
    assert "no healthy device to rebuild on" in text


@pytest.mark.chaos
def test_budget_denial_mid_ladder_degrades_before_probing(tmp_path):
    """The satellite fix pinned end-to-end: restart_limit=2 in all-time
    mode means the elastic rung (attempt 3) is DENIED by the budget —
    exactly one budget unit per restart, however expensive the rung —
    and the run degrades to the sentinel abort without ever probing."""
    params = gol.Params(
        engine="roll", mesh_shape=(1, 1), image_width=16, image_height=16,
        superstep=4, turns=24, soup_density=0.25, soup_seed=11,
        out_dir=tmp_path / "faulted", cycle_check=0, ticker_period=60.0,
        checkpoint_every_turns=4, restart_limit=2,
    )
    (tmp_path / "faulted").mkdir()
    plan = FaultPlan([Fault(2, "device_down", device=0)])
    harness, factory = persistent_harness(params, plan)
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError, match="device_down"):
        supervise(
            params,
            events,
            backend_factory=factory,
            device_probe=harness.device_probe,
        )
    drain(events)
    doc = flight_lib.load_flight_record(
        flight_lib.latest_flight_record(tmp_path / "faulted")
    )
    kinds = [r["kind"] for r in doc["records"]]
    assert kinds.count("restart") == 2
    assert "supervisor_exhausted" in kinds
    # Budget denied BEFORE the elastic rung ran: no probe, no blacklist.
    assert "device_blacklist" not in kinds
    assert mesh_lib.blacklisted() == frozenset()


@pytest.mark.chaos
def test_probe_failure_mid_ladder_still_delivers_the_sentinel(tmp_path):
    """A device_probe that ITSELF raises (the injectable seam failing, or
    a transport error in a custom prober) must degrade to the sentinel
    abort like every sibling failure path — flight dump with the probe
    failure recorded, stream ended — never an escaped exception that
    leaves stream consumers blocked forever."""
    params = gol.Params(
        engine="roll", mesh_shape=(1, 1), image_width=16, image_height=16,
        superstep=4, turns=24, soup_density=0.25, soup_seed=11,
        out_dir=tmp_path / "faulted", cycle_check=0, ticker_period=60.0,
        checkpoint_every_turns=4, restart_limit=5,
    )
    (tmp_path / "faulted").mkdir()
    plan = FaultPlan([Fault(2, "device_down", device=0)])
    harness, factory = persistent_harness(params, plan)

    def broken_probe(devs):
        raise KeyError("probe transport died")

    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError, match="device_down"):
        supervise(
            params, events, backend_factory=factory, device_probe=broken_probe
        )
    drain(events)  # the sentinel arriving IS the assertion
    doc = flight_lib.load_flight_record(
        flight_lib.latest_flight_record(tmp_path / "faulted")
    )
    exhausted = [
        r for r in doc["records"] if r["kind"] == "elastic_exhausted"
    ][0]
    assert exhausted["cause"] == "KeyError"
    assert doc["records"][-1]["kind"] == "abort"


# -- supervisor ladder units ---------------------------------------------------


def test_ladder_tier_names_elastic_rung():
    params = gol.Params(
        turns=8, image_width=16, image_height=16, engine="roll",
        soup_density=0.25, soup_seed=11, ticker_period=60.0, restart_limit=4,
    )
    sup = Supervisor(params, queue.Queue())
    assert sup._ladder_tier(1) == "same"
    assert sup._ladder_tier(2) == "forced-ppermute"
    assert sup._ladder_tier(3) == "elastic"
    assert sup._ladder_tier(4) == "elastic"


def test_plan_elastic_keeps_topology_when_enough_survive():
    """A failure that was NOT device-tied (the probe finds everyone
    healthy) keeps the run's own mesh shape — the elastic rung only
    shrinks when it must — but still re-probes and records."""
    params = gol.Params(
        turns=8, image_width=16, image_height=16, engine="roll",
        soup_density=0.25, soup_seed=11, ticker_period=60.0, restart_limit=4,
    )
    sup = Supervisor(
        params, queue.Queue(), device_probe=lambda devs: (list(devs), [])
    )
    shape, excluded = sup._plan_elastic(3)
    assert shape == (1, 1) and excluded == []
    kinds = [r["kind"] for r in sup.flight.records()]
    assert "device_blacklist" in kinds and "mesh_shrink" not in kinds


def test_plan_elastic_all_condemned_raises():
    params = gol.Params(
        turns=8, image_width=16, image_height=16, engine="roll",
        soup_density=0.25, soup_seed=11, ticker_period=60.0, restart_limit=4,
    )
    sup = Supervisor(
        params, queue.Queue(), device_probe=lambda devs: ([], list(devs))
    )
    with pytest.raises(AllDevicesCondemned):
        sup._plan_elastic(3)


# -- peer heartbeat units ------------------------------------------------------


class TestPeerHeartbeat:
    def test_two_monitors_keep_each_other_alive(self):
        from distributed_gol_tpu.parallel.multihost import PeerHeartbeat

        a = PeerHeartbeat(0.1, process_id=0, num_processes=2)
        b = PeerHeartbeat(0.1, process_id=1, num_processes=2)
        try:
            ha, pa = a._bind()
            hb, pb = b._bind()
            addrs = {0: ("127.0.0.1", pa), 1: ("127.0.0.1", pb)}
            a.start(addrs)
            b.start(addrs)
            # Well past the 3-interval timeout: pings keep both alive.
            time.sleep(0.8)
            assert a.dead_peers() == [] and b.dead_peers() == []
        finally:
            a.stop()
            b.stop()

    def test_dead_peer_detected_within_the_bound(self):
        from distributed_gol_tpu.parallel.multihost import (
            HEARTBEAT_MISS_FACTOR,
            PeerHeartbeat,
        )

        a = PeerHeartbeat(0.1, process_id=0, num_processes=2)
        b = PeerHeartbeat(0.1, process_id=1, num_processes=2)
        try:
            ha, pa = a._bind()
            hb, pb = b._bind()
            addrs = {0: ("127.0.0.1", pa), 1: ("127.0.0.1", pb)}
            a.start(addrs)
            b.start(addrs)
            time.sleep(0.3)
            assert a.dead_peers() == []
            b.stop()  # the "SIGKILL": b goes silent
            t0 = time.monotonic()
            deadline = t0 + 10 * HEARTBEAT_MISS_FACTOR * 0.1  # generous rig slack
            while a.dead_peers() != [1] and time.monotonic() < deadline:
                time.sleep(0.02)
            detected = time.monotonic() - t0
            assert a.dead_peers() == [1], "silent peer never declared dead"
            # Bounded detection: the timeout plus rig slack, nowhere near
            # a coordination-service multi-minute hard-kill.
            assert detected < 10 * HEARTBEAT_MISS_FACTOR * 0.1
        finally:
            a.stop()
            b.stop()

    def test_single_process_run_has_no_peers(self):
        from distributed_gol_tpu.parallel.multihost import PeerHeartbeat

        hb = PeerHeartbeat(0.1, process_id=0, num_processes=1)
        try:
            host, port = hb._bind()
            hb.start({0: (host, port)})
            assert hb.dead_peers() == []
        finally:
            hb.stop()

    def test_interval_validated(self):
        from distributed_gol_tpu.parallel.multihost import PeerHeartbeat

        with pytest.raises(ValueError):
            PeerHeartbeat(0.0, process_id=0, num_processes=2)

    def test_params_validation(self):
        with pytest.raises(ValueError, match="peer_heartbeat_seconds"):
            gol.Params(turns=1, peer_heartbeat_seconds=-1.0)
