"""Pins the README's engine × mesh capability matrix via
``Backend.engine_used`` (round-2 verdict, weak-5: silent fallbacks were
discoverable only by reading source).  Runs on the virtual CPU mesh, so
'auto' resolves its CPU column; the TPU upgrades are covered by the
hardware bench artifacts (`BENCH_r*.json` record the engine actually run).
"""

import pytest

from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.params import Params


def used(engine, mesh=(1, 1), width=4096, height=64, **kw):
    params = Params(
        engine=engine,
        mesh_shape=mesh,
        image_width=width,
        image_height=height,
        turns=20,
        **kw,
    )
    return Backend(params).engine_used


# --- single device ---------------------------------------------------------


# The matrix columns exercise documented downgrades on purpose; their
# warnings are pinned by the dedicated tests below, so the columns ignore
# them (pytest.ini escalates uncaptured engine warnings to errors).
@pytest.mark.filterwarnings("ignore:engine :RuntimeWarning")
def test_single_device_column():
    assert used("roll") == "roll"
    assert used("pallas") == "pallas"  # W % 128 == 0; interpret off-TPU
    assert used("pallas", width=200) == "roll"  # unsupported width
    assert used("packed") == "packed"
    assert used("packed", width=200) == "roll"  # W % 32 != 0
    # Explicit pallas-packed honoured off-TPU (interpret); tiled shape.
    assert used("pallas-packed") == "pallas-packed"
    # Neither tileable (wp % 128) nor VMEM-resident (H % 256): -> packed.
    assert used("pallas-packed", width=640) == "packed"
    # auto on CPU: packed (Pallas upgrades are TPU-only for auto).
    assert used("auto") == "packed"


def test_viewer_runs_prefer_roll():
    # Per-turn-visible run: auto resolves to roll at superstep 1.
    assert used("auto", no_vis=False, flip_events="cell") == "roll"
    assert (
        used("auto", mesh=(4, 1), no_vis=False, flip_events="cell") == "roll"
    )


# --- row mesh --------------------------------------------------------------


def test_row_mesh_column():
    assert used("roll", mesh=(4, 1)) == "roll"
    assert used("packed", mesh=(4, 1)) == "packed"
    # Explicit pallas-packed: T-deep halo kernel on a row mesh.
    assert used("pallas-packed", mesh=(4, 1)) == "pallas-packed"
    assert used("auto", mesh=(4, 1)) == "packed"  # CPU auto
    with pytest.raises(NotImplementedError):
        used("pallas", mesh=(4, 1))


# --- 2-D mesh --------------------------------------------------------------


@pytest.mark.filterwarnings("ignore:engine :RuntimeWarning")
def test_2d_mesh_column():
    assert used("roll", mesh=(2, 4)) == "roll"
    assert used("packed", mesh=(2, 4)) == "packed"
    # Round 7: the T-deep kernel family covers word-aligned 2-D tiles —
    # explicit pallas-packed runs the x-extended tile tier (interpret
    # hermetically here; the in-kernel exchange on TPU pods).
    assert used("pallas-packed", mesh=(2, 2)) == "pallas-packed"
    assert used("pallas-packed", mesh=(2, 4)) == "pallas-packed"
    assert used("auto", mesh=(2, 4)) == "packed"  # CPU auto: no upgrade
    with pytest.raises(NotImplementedError):
        used("pallas", mesh=(2, 2))
    # Per-device width not word-aligned: packed falls back to roll.
    assert used("packed", mesh=(2, 4), width=2048 + 32) == "roll"


@pytest.mark.filterwarnings("ignore:engine :RuntimeWarning")
def test_unsupported_per_device_width_falls_to_roll():
    # 4104 / 4 = 1026, not a multiple of 32 -> word halos unsupported.
    assert used("packed", mesh=(1, 4), width=4104, height=64) == "roll"


# --- fallback visibility (round-3 verdict, weak-5) -------------------------


def test_explicit_engine_downgrade_warns():
    with pytest.warns(RuntimeWarning, match="falling back to 'roll'"):
        used("packed", width=200)
    with pytest.warns(RuntimeWarning, match="falling back to 'packed'"):
        used("pallas-packed", width=640)
    with pytest.warns(RuntimeWarning, match="capability matrix"):
        used("pallas", width=200)


def test_auto_downgrade_warns_on_packable_widths():
    # Global width word-aligned (4128 % 32 == 0) but the per-device strip
    # (1032) is not: auto wanted packed, got roll — the scenario the
    # round-3 verdict flagged as silent.
    with pytest.warns(RuntimeWarning, match="falling back to 'roll'"):
        used("auto", mesh=(1, 4), width=4128, height=64)


def test_auto_2d_mesh_on_tpu_is_policy_not_downgrade(monkeypatch, recwarn):
    """Advisor r4 (updated round 7): auto on a 2-D mesh whose per-device
    width misses the 128-lane quantum resolves to 'packed' BY DESIGN
    (the hardware gate of the 2-D tile tier), so a TPU backend must not
    warn.  The backend is faked to 'tpu' for the resolution only — the
    4096-wide board gives 64-word tiles on (2, 2), under the quantum, so
    the mesh never reaches a Pallas build (supports() gates it first)."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert used("auto", mesh=(2, 2)) == "packed"
    assert not [w for w in recwarn if w.category is RuntimeWarning]
    # Pin the asymmetry: on a SINGLE device (a degenerate row mesh) the
    # same fake backend does prefer pallas-packed, so a width only the
    # packed engine takes (640: wp % 128 != 0, H % 256 != 0) must warn.
    with pytest.warns(RuntimeWarning, match="falling back to 'packed'"):
        assert used("auto", width=640) == "packed"


def test_no_warning_when_engine_honoured_or_policy(recwarn):
    used("packed")  # honoured exactly
    used("auto")  # CPU auto prefers packed and gets it
    used("auto", width=200)  # width unpackable by design: policy, not downgrade
    used("auto", no_vis=False, flip_events="cell")  # per-turn roll is policy
    # Round-6 satellite: per-device strips narrower than one packed word
    # (64 wide over 4 mesh columns -> 16 cells/device) are a documented
    # capability bound — `auto` routing them to roll is policy.  This was
    # the round-5 hermetic suite's 14-warning noise source.
    assert used("auto", mesh=(2, 4), width=64, height=64) == "roll"
    assert not [w for w in recwarn if w.category is RuntimeWarning]
