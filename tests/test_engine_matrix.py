"""Pins the README's engine × mesh capability matrix via
``Backend.engine_used`` (round-2 verdict, weak-5: silent fallbacks were
discoverable only by reading source).  Runs on the virtual CPU mesh, so
'auto' resolves its CPU column; the TPU upgrades are covered by the
hardware bench artifacts (`BENCH_r*.json` record the engine actually run).
"""

import pytest

from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.params import Params


def used(engine, mesh=(1, 1), width=4096, height=64, **kw):
    params = Params(
        engine=engine,
        mesh_shape=mesh,
        image_width=width,
        image_height=height,
        turns=20,
        **kw,
    )
    return Backend(params).engine_used


# --- single device ---------------------------------------------------------


def test_single_device_column():
    assert used("roll") == "roll"
    assert used("pallas") == "pallas"  # W % 128 == 0; interpret off-TPU
    assert used("pallas", width=200) == "roll"  # unsupported width
    assert used("packed") == "packed"
    assert used("packed", width=200) == "roll"  # W % 32 != 0
    # Explicit pallas-packed honoured off-TPU (interpret); tiled shape.
    assert used("pallas-packed") == "pallas-packed"
    # Neither tileable (wp % 128) nor VMEM-resident (H % 256): -> packed.
    assert used("pallas-packed", width=640) == "packed"
    # auto on CPU: packed (Pallas upgrades are TPU-only for auto).
    assert used("auto") == "packed"


def test_viewer_runs_prefer_roll():
    # Per-turn-visible run: auto resolves to roll at superstep 1.
    assert used("auto", no_vis=False, flip_events="cell") == "roll"
    assert (
        used("auto", mesh=(4, 1), no_vis=False, flip_events="cell") == "roll"
    )


# --- row mesh --------------------------------------------------------------


def test_row_mesh_column():
    assert used("roll", mesh=(4, 1)) == "roll"
    assert used("packed", mesh=(4, 1)) == "packed"
    # Explicit pallas-packed: T-deep halo kernel on a row mesh.
    assert used("pallas-packed", mesh=(4, 1)) == "pallas-packed"
    assert used("auto", mesh=(4, 1)) == "packed"  # CPU auto
    with pytest.raises(NotImplementedError):
        used("pallas", mesh=(4, 1))


# --- 2-D mesh --------------------------------------------------------------


def test_2d_mesh_column():
    assert used("roll", mesh=(2, 4)) == "roll"
    assert used("packed", mesh=(2, 4)) == "packed"
    # The T-deep kernel is row-mesh-only by design: documented fallback.
    assert used("pallas-packed", mesh=(2, 2)) == "packed"
    assert used("auto", mesh=(2, 4)) == "packed"
    with pytest.raises(NotImplementedError):
        used("pallas", mesh=(2, 2))
    # Per-device width not word-aligned: packed falls back to roll.
    assert used("packed", mesh=(2, 4), width=2048 + 32) == "roll"


def test_unsupported_per_device_width_falls_to_roll():
    # 4104 / 4 = 1026, not a multiple of 32 -> word halos unsupported.
    assert used("packed", mesh=(1, 4), width=4104, height=64) == "roll"
