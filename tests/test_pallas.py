"""Pallas stencil kernel: bit-identity vs the roll stencil and the oracle.

On CPU these run in interpret mode (the kernel's hermetic gate, SURVEY.md §7
stage 5); the same kernel compiles via Mosaic on TPU, where bench.py
compares it against the roll baseline.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.models.life import CONWAY, DAY_AND_NIGHT, HIGHLIFE, SEEDS
from distributed_gol_tpu.ops import pallas_stencil as ps
from distributed_gol_tpu.ops.stencil import steps_with_counts, superstep
from tests.conftest import random_board
from tests.oracle import oracle_step


class TestSupports:
    def test_lane_rule(self):
        assert ps.supports((512, 512))
        assert ps.supports((8, 128))
        # Real-TPU constraint: HBM slice offsets must be 8-aligned, so H
        # needs a multiple-of-8 tile height — H % 8 != 0 is unsupported.
        assert not ps.supports((100, 128))
        assert not ps.supports((16, 16))  # W % 128 != 0
        assert not ps.supports((7, 128))  # H below the minimum tile height

    def test_build_rejects_unsupported(self):
        with pytest.raises(ValueError):
            ps._build_step((16, 16), CONWAY, True)


class TestBitIdentity:
    @pytest.mark.parametrize(
        "shape", [(8, 128), (64, 256), (512, 512), (96, 384), (104, 128)]
    )
    def test_step_vs_roll(self, rng, shape):
        b = random_board(rng, *shape)
        table = jnp.asarray(CONWAY.table)
        roll_out = np.asarray(superstep(jnp.asarray(b), table, 1))
        pallas_out = np.asarray(ps.make_step_fn()(jnp.asarray(b)))
        np.testing.assert_array_equal(pallas_out, roll_out)

    @pytest.mark.parametrize("rule", [HIGHLIFE, SEEDS, DAY_AND_NIGHT], ids=str)
    def test_rules_vs_oracle(self, rng, rule):
        b = random_board(rng, 64, 128)
        out = np.asarray(ps.make_step_fn(rule)(jnp.asarray(b)))
        np.testing.assert_array_equal(out, oracle_step(b, rule))

    def test_superstep_and_counts(self, rng):
        b = random_board(rng, 128, 128)
        table = jnp.asarray(CONWAY.table)
        ref_final, ref_counts = steps_with_counts(jnp.asarray(b), table, 20)
        fin, counts = ps.make_steps_with_counts()(jnp.asarray(b), 20)
        np.testing.assert_array_equal(np.asarray(fin), np.asarray(ref_final))
        np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_counts))

    def test_wrap_seams(self):
        """Gliders crossing every tile boundary and the torus seam: 512-tall
        board forces multiple tiles; run long enough to cross them."""
        b = np.zeros((512, 128), dtype=np.uint8)
        # glider headed down-right
        for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
            b[y, x] = 255
        table = jnp.asarray(CONWAY.table)
        roll_b, pallas_b = jnp.asarray(b), jnp.asarray(b)
        sstep = ps.make_superstep()
        for _ in range(60):
            roll_b = superstep(roll_b, table, 16)
            pallas_b = sstep(pallas_b, 16)
        np.testing.assert_array_equal(np.asarray(pallas_b), np.asarray(roll_b))
        assert int(np.asarray(pallas_b).sum()) // 255 == 5  # glider intact


class TestEngineSelection:
    def test_pallas_engine_golden_512(self, tmp_path, input_images, golden_images):
        """Full run() with engine='pallas' on the 512² golden path."""
        import queue

        p = gol.Params(
            turns=100, image_width=512, image_height=512,
            images_dir=input_images, out_dir=tmp_path, engine="pallas",
        )
        events: queue.Queue = queue.Queue()
        gol.run(p, events)
        while events.get(timeout=60) is not None:
            pass
        assert (tmp_path / "512x512x100.pgm").read_bytes() == (
            golden_images / "512x512x100.pgm"
        ).read_bytes()

    def test_pallas_engine_falls_back_small_board(self, tmp_path, input_images, golden_images):
        import queue

        p = gol.Params(
            turns=100, image_width=16, image_height=16,
            images_dir=input_images, out_dir=tmp_path, engine="pallas",
        )
        events: queue.Queue = queue.Queue()
        gol.run(p, events)
        while events.get(timeout=60) is not None:
            pass
        assert (tmp_path / "16x16x100.pgm").read_bytes() == (
            golden_images / "16x16x100.pgm"
        ).read_bytes()
