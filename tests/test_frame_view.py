"""Device-side downsampling for the viewer (SURVEY.md §7 hard part 4).

The reference renders every pixel every turn (``sdl/window.go:56-64``) —
fine at 512², catastrophic at 16384² where the flip-mask fetch alone is
268 MB/turn.  Above ``Params._FLIP_VIEW_MAX_CELLS`` the viewer is fed
``FrameReady`` events instead: the board max-pools ON DEVICE to at most
``frame_max`` cells, so the per-turn host transfer is bounded regardless
of board size.
"""

import io
import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.ops import stencil
from distributed_gol_tpu.viewer.loop import run_terminal


def make_params(tmp_path, images_dir, size, **kw):
    defaults = dict(
        turns=3,
        image_width=size,
        image_height=size,
        images_dir=images_dir,
        out_dir=tmp_path,
        no_vis=False,
        superstep=0,
        engine="roll",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def write_soup(images_dir, size, density=0.1, seed=3):
    rng = np.random.default_rng(seed)
    board = np.where(rng.random((size, size)) < density, 255, 0).astype(np.uint8)
    from distributed_gol_tpu.engine.pgm import write_pgm

    write_pgm(images_dir / f"{size}x{size}.pgm", board)
    return board


def test_mode_selection():
    small = gol.Params(image_width=512, image_height=512, no_vis=False)
    big = gol.Params(image_width=4096, image_height=4096, no_vis=False)
    assert small.wants_flips() and not small.wants_frames()
    assert big.wants_frames() and not big.wants_flips()
    # Explicit flip modes are the exact reference contract and always win.
    exact = gol.Params(
        image_width=4096, image_height=4096, no_vis=False, flip_events="batch"
    )
    assert exact.wants_flips() and not exact.wants_frames()
    # Headless runs feed no viewer at all.
    headless = gol.Params(image_width=4096, image_height=4096, no_vis=True)
    assert not headless.wants_flips() and not headless.wants_frames()


def test_frame_pool_keeps_trailing_cells():
    """Non-divisible board sizes are zero-padded, not cropped: live cells in
    the trailing rows/cols appear in the frame (advisor finding r2), and the
    device pool agrees with the host-side viewer downsample."""
    from distributed_gol_tpu.viewer import render as R

    b = np.zeros((13, 10), np.uint8)
    b[12, 9] = 255
    pooled = np.asarray(stencil.frame_pool(b, 3, 3))
    assert pooled.shape == (5, 4)
    assert pooled[4, 3] == 255
    np.testing.assert_array_equal(pooled, R.downsample(b, 5, 4))


def test_frame_stride_samples_exact_turns(tmp_path):
    """frame_stride=4: the sim advances exactly, TurnComplete stays dense,
    one FrameReady per stride delivered before its own turn's
    TurnComplete, and each frame equals the true pooled board at that
    turn (cross-checked against a per-turn reference run)."""
    import distributed_gol_tpu as gol
    from distributed_gol_tpu.engine.events import FrameReady, TurnComplete

    size, turns = 2048, 10
    images = tmp_path / "images"
    images.mkdir()
    write_soup(images, size)
    params = make_params(tmp_path, images, size, turns=turns, frame_stride=4)
    assert params.wants_frames() and params.runtime_superstep() == 4

    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = []
    while (e := events.get(timeout=120)) is not None:
        stream.append(e)

    tc = [e.completed_turns for e in stream if isinstance(e, TurnComplete)]
    assert tc == list(range(1, turns + 1))  # dense despite the stride
    frames = [e for e in stream if isinstance(e, FrameReady)]
    assert [f.completed_turns for f in frames] == [0, 4, 8, 10]  # incl. rem
    for f in frames[1:]:
        # frame before its own TurnComplete
        i_f = stream.index(f)
        i_t = next(
            i for i, e in enumerate(stream)
            if isinstance(e, TurnComplete)
            and e.completed_turns == f.completed_turns
        )
        assert i_f < i_t

    # Ground truth: a reference run's board at turn 8, pooled.
    ref = make_params(tmp_path / "ref", images, size, turns=8)
    (tmp_path / "ref").mkdir()
    ev2: queue.Queue = queue.Queue()
    gol.run(ref, ev2)
    while (e := ev2.get(timeout=120)) is not None:
        pass
    from distributed_gol_tpu.engine.pgm import read_pgm

    board8 = read_pgm(tmp_path / "ref" / f"{size}x{size}x8.pgm")
    fy, fx = params.frame_factors()
    want = np.asarray(stencil.frame_pool(board8, fy, fx))
    np.testing.assert_array_equal(frames[2].frame, want)


def test_4096_viewer_transfer_is_bounded(tmp_path):
    """The per-turn host transfer for a 4096² viewer turn is the pooled
    frame: ≤ frame_max cells (256 KB), not the 16 MB board."""
    size = 4096
    images = tmp_path / "images"
    images.mkdir()
    write_soup(images, size)
    params = make_params(tmp_path, images, size, turns=2)
    assert params.wants_frames()
    fy, fx = params.frame_factors()
    assert (fy, fx) == (8, 8)

    backend = Backend(params)
    from distributed_gol_tpu.engine.pgm import read_pgm

    board = backend.put(read_pgm(params.input_path))
    new_board, count, frame = backend.run_turn_with_frame(board, fy, fx)

    assert frame.shape == (512, 512)
    assert frame.nbytes <= 1 << 20  # ≤ 1 MB crosses to the host
    # The frame is the true device-side max-pool of the advanced board.
    want = np.asarray(
        stencil.frame_pool(backend.fetch(new_board), fy, fx)
    )
    np.testing.assert_array_equal(frame, want)
    assert frame.max() > 0


def test_viewer_renders_from_frames(tmp_path):
    """End-to-end: a big-board run emits FrameReady (no flips), and the
    terminal viewer renders from them."""
    size = 2048  # > _FLIP_VIEW_MAX_CELLS (2^21), small enough for CI
    images = tmp_path / "images"
    images.mkdir()
    write_soup(images, size)
    params = make_params(tmp_path, images, size, turns=3)
    assert params.wants_frames()

    events: queue.Queue = queue.Queue()
    gol.start(params, events)

    # Tee the stream so we can both inspect and render it.
    seen = []
    tee: queue.Queue = queue.Queue()

    def pump():
        while True:
            e = events.get()
            seen.append(e)
            tee.put(e)
            if e is None:
                return

    import threading

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    out = io.StringIO()
    final = run_terminal(params, tee, max_fps=10_000, out=out)
    t.join(timeout=30)

    frames = [e for e in seen if isinstance(e, gol.FrameReady)]
    flips = [
        e for e in seen if isinstance(e, (gol.CellFlipped, gol.CellsFlipped))
    ]
    # Initial frame + one per turn; zero flip traffic.
    assert len(frames) == params.turns + 1 and not flips
    assert all(np.asarray(f.frame).nbytes <= 1 << 20 for f in frames)
    # Frames precede their TurnComplete (the flip-ordering contract).
    for turn in range(1, params.turns + 1):
        idx_frame = next(
            i
            for i, e in enumerate(seen)
            if isinstance(e, gol.FrameReady) and e.completed_turns == turn
        )
        idx_tc = next(
            i
            for i, e in enumerate(seen)
            if isinstance(e, gol.TurnComplete) and e.completed_turns == turn
        )
        assert idx_frame < idx_tc
    assert final is not None and final.completed_turns == params.turns
    assert out.getvalue()  # something was actually drawn


class TestLatencyAdaptiveStride:
    """frame_stride=0 (the default): the controller measures the
    frame-fetch round-trip at viewer start and raises the effective
    stride on slow links (round-6 satellite; the round-5 tunnel ran a
    512² viewer at 9 gens/s because stride 1 paid ~110 ms per
    generation).  The link is faked via ``_measure_frame_rtt`` so the
    policy is deterministic on any rig."""

    def test_policy_math(self):
        from distributed_gol_tpu.engine.controller import Controller

        auto = Controller._auto_frame_stride
        # Local links: keep the reference-faithful frame-per-turn cadence.
        assert auto(0.001, 0.003) == 1
        assert auto(0.019, 0.04) == 1
        # The round-5 tunnel (~110 ms fetch), ~2 ms generations: stride
        # ~= rtt / t_gen -> ~55 generations per frame, i.e. ~55x more
        # gens/s at the same fps.
        assert auto(0.110, 0.112) == 55
        # Effectively free generations: bounded at _STRIDE_MAX.
        assert auto(0.110, 0.110) == Controller._STRIDE_MAX
        # Slow generations dominate: nothing to win, stride stays low.
        assert auto(0.030, 0.330) == 1

    def _run(self, tmp_path, monkeypatch, fake_rtt, turns=12, **kw):
        import queue as q

        from distributed_gol_tpu.engine.controller import Controller
        from distributed_gol_tpu.engine.events import FrameReady, TurnComplete

        size = 2048
        images = tmp_path / "images"
        images.mkdir(exist_ok=True)
        write_soup(images, size)
        params = make_params(tmp_path, images, size, turns=turns, **kw)
        assert params.wants_frames()
        if fake_rtt is not None:
            monkeypatch.setattr(
                Controller, "_measure_frame_rtt",
                lambda self, board, fy, fx, turn=0, probes=3, rect=None: (
                    fake_rtt
                ),
            )
        else:
            def _boom(self, board, fy, fx, turn=0, probes=3, rect=None):
                raise AssertionError(
                    "RTT probe must not run with an explicit frame_stride"
                )

            monkeypatch.setattr(Controller, "_measure_frame_rtt", _boom)
        events: q.Queue = q.Queue()
        ctl = Controller(params, events)
        ctl.run()
        stream = []
        while (e := events.get(timeout=120)) is not None:
            stream.append(e)
        tc = [e.completed_turns for e in stream if isinstance(e, TurnComplete)]
        frames = [e.completed_turns for e in stream if isinstance(e, FrameReady)]
        return ctl, tc, frames

    def test_slow_link_raises_stride_stream_stays_dense(
        self, tmp_path, monkeypatch
    ):
        # A fat fake RTT: after the two warm stride-1 dispatches the
        # stride must rise, TurnComplete stays dense and exact, frames
        # keep frame-before-own-TurnComplete cadence (asserted by the
        # existing contract tests; here: turn accounting + stride).
        ctl, tc, frames = self._run(tmp_path, monkeypatch, fake_rtt=10.0)
        assert ctl.frame_stride_effective == ctl._STRIDE_MAX
        assert tc == list(range(1, 13))  # dense despite the stride
        # Warm-up frames at stride 1, then strided to the end.
        assert frames[0] == 0 and 1 in frames and 2 in frames
        assert frames[-1] == 12

    def test_local_link_keeps_frame_per_turn(self, tmp_path, monkeypatch):
        ctl, tc, frames = self._run(tmp_path, monkeypatch, fake_rtt=0.0)
        assert ctl.frame_stride_effective == 1
        assert frames == list(range(0, 13))  # initial + one per turn
        assert tc == list(range(1, 13))

    def test_explicit_stride_wins(self, tmp_path, monkeypatch):
        # frame_stride=4: the probe never runs (monkeypatched to raise),
        # the cadence is exactly the explicit stride.
        ctl, tc, frames = self._run(
            tmp_path, monkeypatch, fake_rtt=None, frame_stride=4
        )
        assert ctl.frame_stride_effective == 4
        assert frames == [0, 4, 8, 12]
        assert tc == list(range(1, 13))


def test_sharded_frame_view(tmp_path):
    """Frames × sharding: the device-pooled viewer path over a mesh (the
    pooling reduction compiles across shards; the fetched frame is the
    same one a single-device run produces)."""
    size = 2048
    images = tmp_path / "images"
    images.mkdir()
    write_soup(images, size)
    params = make_params(
        tmp_path, images, size, turns=2, mesh_shape=(2, 4)
    )
    assert params.wants_frames()

    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    seen = []
    while (e := events.get(timeout=60)) is not None:
        seen.append(e)
    frames = [e for e in seen if isinstance(e, gol.FrameReady)]
    assert len(frames) == params.turns + 1

    single = make_params(tmp_path / "s", images, size, turns=2)
    (tmp_path / "s").mkdir(exist_ok=True)
    ev2: queue.Queue = queue.Queue()
    gol.run(single, ev2)
    seen2 = []
    while (e := ev2.get(timeout=60)) is not None:
        seen2.append(e)
    frames2 = [e for e in seen2 if isinstance(e, gol.FrameReady)]
    assert len(frames2) == len(frames)
    for a, b in zip(frames, frames2):
        np.testing.assert_array_equal(np.asarray(a.frame), np.asarray(b.frame))
