"""AliveCells: the array-backed alive-cell sequence carried by
FinalTurnComplete.

The reference returns ``[]util.Cell`` (``gol/distributor.go:153-166``) and
tests compare it as a multiset (``gol_test.go:58-86``); this container keeps
that consumer contract (iteration yields Cell, len, indexing, equality with
plain cell sequences) while costing O(1) Python objects at construction so a
16384² finalize stays sub-second (VERDICT r1 weak #4).
"""

import time

import numpy as np
import pytest

from distributed_gol_tpu.utils.cell import AliveCells, Cell, board_from_alive_cells


def _board():
    rng = np.random.default_rng(7)
    return np.where(rng.random((32, 48)) < 0.3, 255, 0).astype(np.uint8)


def test_matches_cell_list_contract():
    board = _board()
    cells = AliveCells.from_board(board)
    ys, xs = np.nonzero(board)
    expected = [Cell(int(x), int(y)) for x, y in zip(xs, ys)]
    assert len(cells) == len(expected)
    assert list(cells) == expected  # iteration yields Cell NamedTuples
    assert cells[0] == expected[0] and cells[-1] == expected[-1]
    assert cells == expected  # sequence equality against a plain list
    assert {(c.x, c.y) for c in cells} == {(c.x, c.y) for c in expected}


def test_empty_equals_empty_tuple():
    # The detach path emits FinalTurnComplete(turn, ()) and tests compare
    # with (); an empty AliveCells must agree both ways.
    empty = AliveCells.from_board(np.zeros((8, 8), dtype=np.uint8))
    assert len(empty) == 0
    assert empty == ()
    assert not (empty != ())


def test_roundtrip_through_board():
    board = _board()
    cells = AliveCells.from_board(board)
    rebuilt = board_from_alive_cells(list(cells), board.shape[1], board.shape[0])
    assert np.array_equal(rebuilt, board)


def test_slice_returns_alive_cells():
    cells = AliveCells.from_board(_board())
    head = cells[:5]
    assert isinstance(head, AliveCells)
    assert list(head) == list(cells)[:5]


@pytest.mark.slow
def test_large_board_finalize_is_fast():
    # 8192² at 30% density: ~20M alive cells.  Construction must be
    # array-speed, not object-materialisation speed.  The bound is a
    # RATIO against a same-run array-op baseline (np.flatnonzero of the
    # same board), so a contended 1-core rig slows numerator and
    # denominator together — the absolute 1.0 s form flaked exactly when
    # both suites shared the rig (round-5 verdict, weak-1).
    rng = np.random.default_rng(0)
    board = np.where(rng.random((8192, 8192)) < 0.3, 255, 0).astype(np.uint8)
    t0 = time.perf_counter()
    base = np.flatnonzero(board)
    t_base = time.perf_counter() - t0
    t0 = time.perf_counter()
    cells = AliveCells.from_board(board)
    dt = time.perf_counter() - t0
    assert len(cells) == base.size
    # from_board is one flatnonzero + two vectorised int32 ops: 12× the
    # measured flatnonzero (plus a scheduling-noise floor) leaves wide
    # margin while staying orders of magnitude under per-cell object
    # materialisation (~20M Python objects).
    assert dt < 12 * t_base + 0.05, (
        f"AliveCells.from_board took {dt:.2f}s vs same-run flatnonzero "
        f"baseline {t_base:.2f}s"
    )
