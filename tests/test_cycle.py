"""Whole-board cycle detection + exact fast-forward (Params.cycle_check).

The reference's own 512² test board settles into a period-2 cycle near
turn 5k (``check/alive/512x512.csv`` tail: 5565/5567 forever), after which
its per-turn RPC loop keeps paying full price for every remaining turn of
the default 10^10-turn run (``main.go:33``).  The cycle probe proves
period-6 stability on device and then delivers the rest of the run from
the 6 cycle phases — bit-identical events, counts, snapshots, and final
board, at zero device supersteps.  These tests pin that exactness.
"""

import queue

import numpy as np

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine import pgm
from distributed_gol_tpu.engine.events import (
    CycleDetected,
    FinalTurnComplete,
    ImageOutputComplete,
    TurnComplete,
    TurnsCompleted,
)
from distributed_gol_tpu.engine.session import Session

from tests.oracle import oracle_run


def blinker_board(h=16, w=16) -> np.ndarray:
    """A still life (block) + a period-2 oscillator (blinker): globally
    periodic from turn 0, so the first probe proves the cycle."""
    b = np.zeros((h, w), np.uint8)
    b[1:3, 1:3] = 255  # block
    b[8, 5:8] = 255  # horizontal blinker
    return b


def write_board(images_dir, board):
    images_dir.mkdir(parents=True, exist_ok=True)
    h, w = board.shape
    pgm.write_pgm(images_dir / f"{w}x{h}.pgm", board)


def make_params(tmp_path, **kw):
    defaults = dict(
        turns=100,
        image_width=16,
        image_height=16,
        images_dir=tmp_path / "images",
        out_dir=tmp_path,
        engine="roll",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def drain(events):
    out = []
    while (e := events.get(timeout=120)) is not None:
        out.append(e)
    return out


def alive_set(board):
    ys, xs = np.nonzero(board)
    return {(int(x), int(y)) for y, x in zip(ys, xs)}


def test_fast_forward_a_billion_turns_batch(tmp_path):
    """10^9+1 turns complete near-instantly once the cycle is proved; the
    final board is the exact phase (odd turn => flipped blinker)."""
    board = blinker_board()
    write_board(tmp_path / "images", board)
    turns = 10**9 + 1
    params = make_params(
        tmp_path, turns=turns, turn_events="batch", superstep=4, cycle_check=2
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)

    cycles = [e for e in stream if isinstance(e, CycleDetected)]
    assert len(cycles) == 1 and cycles[0].period == 6

    ranges = [
        (e.first_turn, e.completed_turns)
        for e in stream
        if isinstance(e, TurnsCompleted)
    ]
    assert ranges[0][0] == 1 and ranges[-1][1] == turns
    for (_, l0), (f1, _) in zip(ranges, ranges[1:]):
        assert f1 == l0 + 1

    expected = oracle_run(board, 1)  # odd total turns: phase 1 of period 2
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(expected)
    out = pgm.read_pgm(tmp_path / f"16x16x{turns}.pgm")
    assert np.array_equal(out, expected)


def test_fast_forward_per_turn_stream_stays_dense(tmp_path):
    board = blinker_board()
    write_board(tmp_path / "images", board)
    turns = 200_000
    params = make_params(tmp_path, turns=turns, superstep=8, cycle_check=1)
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)

    assert any(isinstance(e, CycleDetected) for e in stream)
    tc = [e.completed_turns for e in stream if isinstance(e, TurnComplete)]
    assert tc == list(range(1, turns + 1))
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(board)  # even turns: phase 0


def test_fast_forward_adaptive_superstep(tmp_path):
    """The adaptive (superstep=0) dispatch ladder probes and fast-forwards
    too — the default configuration of a headless run."""
    board = blinker_board()
    write_board(tmp_path / "images", board)
    turns = 10**9
    params = make_params(
        tmp_path, turns=turns, turn_events="batch", superstep=0, cycle_check=2
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert any(isinstance(e, CycleDetected) for e in stream)
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(board)


def test_active_board_never_fires_and_stays_golden(
    tmp_path, input_images, golden_images
):
    """A board that has not settled must never fast-forward: probes run
    (cycle_check=1) but return false, and the run lands exactly on the
    reference golden board."""
    params = gol.Params(
        turns=100,
        image_width=64,
        image_height=64,
        images_dir=input_images,
        out_dir=tmp_path,
        engine="roll",
        superstep=4,
        cycle_check=1,
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert not any(isinstance(e, CycleDetected) for e in stream)
    golden = pgm.read_pgm(golden_images / "64x64x100.pgm")
    out = pgm.read_pgm(tmp_path / "64x64x100.pgm")
    assert np.array_equal(out, golden)


def test_ticker_count_matches_cycle_phase(tmp_path):
    """AliveCellsCount during/after fast-forward reports the phase-exact
    count: blinker+block is 7 alive in both phases, so latch the final
    pair and check a board whose phases differ in count."""
    # A beacon (period 2: 8 alive then 6 alive) pins phase-dependent counts.
    b = np.zeros((16, 16), np.uint8)
    b[2:4, 2:4] = 255
    b[4:6, 4:6] = 255
    assert int((oracle_run(b, 1) != 0).sum()) == 6
    write_board(tmp_path / "images", b)
    turns = 10**6 + 1
    params = make_params(
        tmp_path, turns=turns, turn_events="batch", superstep=4, cycle_check=2
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert any(isinstance(e, CycleDetected) for e in stream)
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    # Odd turn: the 6-alive phase.
    assert len(final.alive) == 6
    expected = oracle_run(b, 1)
    out = pgm.read_pgm(tmp_path / f"16x16x{turns}.pgm")
    assert np.array_equal(out, expected)


def test_keys_during_fast_forward_detach_resume_snapshot(tmp_path):
    """'s' and 'q' during per-turn fast-forward emission operate on the
    true phase board for the emitted turn; the detach checkpoint resumes
    to the exact final phase."""
    board = blinker_board()
    write_board(tmp_path / "images", board)
    turns = 10**7  # emission alone takes long enough for keys to land
    session = Session()
    params = make_params(tmp_path, turns=turns, superstep=4, cycle_check=1)
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    t = gol.start(params, events, keys, session)

    saw_cycle = False
    stream = []
    while (e := events.get(timeout=120)) is not None:
        if not isinstance(e, TurnComplete):  # bound test memory
            stream.append(e)
        if isinstance(e, CycleDetected) and not saw_cycle:
            saw_cycle = True
            keys.put("s")
            keys.put("q")
    t.join(timeout=120)
    assert saw_cycle

    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn < turns
    # Checkpoint world is the exact phase board for the detach turn.
    assert np.array_equal(ckpt.world, oracle_run(board, ckpt.turn % 2))
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == ckpt.turn

    snaps = [e for e in stream if isinstance(e, ImageOutputComplete)]
    assert len(snaps) == 1
    snap_turn = int(snaps[0].filename.split("x")[2].removesuffix("current"))
    snap = pgm.read_pgm(tmp_path / f"{snaps[0].filename}.pgm")
    assert np.array_equal(snap, oracle_run(board, snap_turn % 2))

    # Re-park the inspected checkpoint (check_states is consume-once),
    # then resume in batch mode: the rest completes instantly.
    session.pause(True, world=ckpt.world, turn=ckpt.turn)
    resumed = make_params(
        tmp_path,
        turns=turns,
        turn_events="batch",
        superstep=4,
        cycle_check=1,
    )
    events2: queue.Queue = queue.Queue()
    gol.run(resumed, events2, session=session)
    final2 = [e for e in drain(events2) if isinstance(e, FinalTurnComplete)][0]
    assert final2.completed_turns == turns
    assert set(final2.alive) == alive_set(board)  # even total: phase 0


def test_probe_engines_and_mesh(tmp_path):
    """Backend.cycle_probe_async is exact for the packed engine and on a
    sharded mesh (the equality reduces across shards)."""
    from distributed_gol_tpu.engine.backend import Backend

    blinker = blinker_board(64, 64)
    glider = np.zeros((64, 64), np.uint8)
    glider[1, 2] = glider[2, 3] = glider[3, 1:4] = 255

    for kw in (
        dict(engine="packed", superstep=8),
        dict(engine="roll", superstep=8, mesh_shape=(2, 2)),
    ):
        params = gol.Params(
            image_width=64, image_height=64, turns=100, **kw
        )
        backend = Backend(params)
        assert bool(backend.cycle_probe_async(backend.put(blinker)))
        assert not bool(backend.cycle_probe_async(backend.put(glider)))
        counts = backend.cycle_counts(backend.put(blinker))
        assert counts.shape == (6,) and all(int(c) == 7 for c in counts)


def test_cycle_check_disabled(tmp_path):
    board = blinker_board()
    write_board(tmp_path / "images", board)
    params = make_params(
        tmp_path, turns=3000, turn_events="batch", superstep=8, cycle_check=0
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert not any(isinstance(e, CycleDetected) for e in stream)
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == 3000
