"""Independent NumPy oracle for life-like rules on a torus.

Deliberately written with a different algorithm from the engine under test
(padded-array slicing here vs. jnp.roll / Pallas there) so shared bugs are
unlikely.  Mirrors the *behaviour* of the reference kernel
``server/server.go:33-75`` (B3/S23 on {0,255} bytes, toroidal wrap).
"""

from __future__ import annotations

import numpy as np

from distributed_gol_tpu.models.life import CONWAY, LifeRule


def oracle_step(board: np.ndarray, rule: LifeRule = CONWAY) -> np.ndarray:
    alive = (board == 255).astype(np.int64)
    padded = np.pad(alive, 1, mode="wrap")
    counts = np.zeros_like(alive)
    for dy in (0, 1, 2):
        for dx in (0, 1, 2):
            if dy == 1 and dx == 1:
                continue
            h, w = alive.shape
            counts += padded[dy : dy + h, dx : dx + w]
    out = np.zeros_like(board, dtype=np.uint8)
    for n in range(9):
        if n in rule.birth:
            out[(alive == 0) & (counts == n)] = 255
        if n in rule.survive:
            out[(alive == 1) & (counts == n)] = 255
    return out


def oracle_run(board: np.ndarray, turns: int, rule: LifeRule = CONWAY) -> np.ndarray:
    for _ in range(turns):
        board = oracle_step(board, rule)
    return board
