"""The quiet-measurement protocol helpers (round 6, utils/measure.py):
spread/median math pinned, amplification sizing, malformed-record
rejection, and the on-device repeat loop — all deterministic on CPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops import packed
from distributed_gol_tpu.utils import measure


class TestStats:
    def test_median_is_upper_median(self):
        # The one convention every artifact row uses (sorted[n//2]) —
        # the BENCH_ICI_PR1-era rows must stay comparable.
        assert measure.median([3.0, 1.0, 2.0]) == 2.0
        assert measure.median([4.0, 1.0, 2.0, 3.0]) == 3.0
        assert measure.median([5.0]) == 5.0
        with pytest.raises(ValueError):
            measure.median([])

    def test_spread_is_full_envelope_over_median(self):
        assert measure.spread([100.0, 90.0, 110.0]) == pytest.approx(0.2)
        assert measure.spread([7.0]) == 0.0

    def test_summarize_block(self):
        s = measure.summarize([90.0, 110.0, 100.0])
        assert s == {
            "reps": 3,
            "median": 100.0,
            "spread": pytest.approx(0.2),
            "rates": [90.0, 100.0, 110.0],
        }
        assert measure.summarize([42.0])["spread"] == 0.0

    def test_summarize_rejects_broken_measurements(self):
        with pytest.raises(measure.MalformedRecord):
            measure.summarize([])
        with pytest.raises(measure.MalformedRecord):
            measure.summarize([100.0, 0.0])
        with pytest.raises(measure.MalformedRecord):
            measure.summarize([100.0, float("nan")])
        with pytest.raises(measure.MalformedRecord):
            measure.summarize([-5.0])


class TestAmplification:
    def test_dwarfs_noise_and_target(self):
        # 1 ms unit, 10 ms noise, default 20x mult -> 0.5 s target wins
        # over 0.2 s of noise floor: 500 units.
        assert measure.pick_amplification(0.001, 0.010) == 500
        # Loud noise: 20 x 0.11 s = 2.2 s >> target -> 2200 units.
        assert measure.pick_amplification(0.001, 0.110) == 2200
        # Slow unit: one dispatch already dwarfs everything.
        assert measure.pick_amplification(2.0, 0.110) == 2
        assert measure.pick_amplification(10.0, 0.0) == 1

    def test_cap_and_degenerate_unit(self):
        assert measure.pick_amplification(1e-9, 0.1, cap=4096) == 4096
        assert measure.pick_amplification(0.0, 0.1) == 4096
        assert measure.pick_amplification(0.001, 0.0, target_seconds=0.25,
                                          cap=100) == 100


class TestHeadlineLint:
    def _row(self, **kw):
        row = {
            "metric": "m",
            "value": 1.0,
            "reps": 3,
            "median": 10.0,
            "spread": 0.1,
            "rates": [9.0, 10.0, 11.0],
        }
        row.update(kw)
        return row

    def test_clean_record_passes(self):
        record = self._row(nested=self._row(), rows=[self._row(), {"no": 1}])
        assert measure.check_headline_stats(record) == []
        measure.require_headline_stats(record)  # no raise

    def test_bare_single_sample_row_rejected(self):
        # The round-5 shape: a metric with only a value — exactly what
        # the acceptance bar outlaws.
        problems = measure.check_headline_stats(
            {"metric": "m", "value": 123.0}
        )
        assert problems and "reps" in problems[0]
        with pytest.raises(measure.MalformedRecord):
            measure.require_headline_stats({"metric": "m", "value": 123.0})

    def test_malformed_blocks_rejected(self):
        assert measure.check_headline_stats(self._row(reps=0))
        assert measure.check_headline_stats(self._row(median=-1.0))
        assert measure.check_headline_stats(self._row(median=None))
        assert measure.check_headline_stats(self._row(spread=-0.1))
        assert measure.check_headline_stats(self._row(spread=None))
        assert measure.check_headline_stats(self._row(rates=[1.0]))  # != reps

    def test_single_rep_row_may_omit_spread_only_as_zero(self):
        # reps == 1 (pilot rows): spread None is tolerated, numbers are
        # still required.
        row = self._row(reps=1, spread=None, rates=[10.0])
        assert measure.check_headline_stats(row) == []

    def test_nested_violation_carries_path(self):
        record = {"metric": "top", **self._row(), "inner": {"metric": "bad",
                                                            "value": 1.0}}
        problems = measure.check_headline_stats(record)
        assert len(problems) == 1 and "$.inner" in problems[0]


class TestRepeatLoop:
    def test_device_repeat_matches_chained_supersteps(self, rng):
        """The lax.fori_loop amplification is the SAME simulation: 4
        on-device reps of 6 generations == one 24-generation superstep,
        bit for bit (seeded board, packed engine, CPU)."""
        b = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        p = packed.pack(jnp.asarray(b))
        run = lambda x, t: packed.superstep(x, CONWAY, t)  # noqa: E731
        repeated = measure.device_repeat(run, 6, 4)
        np.testing.assert_array_equal(
            np.asarray(repeated(p)), np.asarray(run(p, 24))
        )

    def test_chain_issues_n_calls(self):
        calls = []

        def run(x):
            calls.append(x)
            return x + 1

        assert measure.chain(run, 0, 5) == 5
        assert calls == [0, 1, 2, 3, 4]

    def test_quiet_rates_shape_and_accounting(self, rng):
        """End-to-end on a real (tiny, CPU) engine: the stats block is
        well-formed, rates are positive, and the protocol fields record
        how quiet the measurement was."""
        b = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        p = packed.pack(jnp.asarray(b))
        run = lambda x, t: packed.superstep(x, CONWAY, t)  # noqa: E731
        p = run(p, 6)  # compile outside the measurement

        def sync(x):
            return np.asarray(x)[0, 0]

        _, stats = measure.quiet_rates(
            lambda x: run(x, 6),
            p,
            gens_per_call=6,
            sync=sync,
            reps=3,
            target_seconds=0.02,
        )
        assert stats["reps"] == 3 and len(stats["rates"]) == 3
        assert stats["median"] > 0 and stats["spread"] >= 0
        assert stats["amp"] >= 1 and stats["unit_s"] > 0
        assert measure.check_headline_stats({"metric": "m", **stats}) == []
