"""Pod federation chaos matrix (ISSUE 17).

The broker tier's robustness legs, asserted hermetically on CPU over
real loopback sockets:

- **SIGKILL failover**: a REAL subprocess pod is SIGKILLed mid-run by
  the ``pod_down`` chaos driver; the broker's prober condemns it, the
  stranded tenant is re-adopted on a survivor from its newest intact
  durable checkpoint and runs to completion BIT-IDENTICAL to a
  fault-free oracle; the healthy pod's own tenant is undisturbed; the
  failover is truthful in ``broker.failovers`` + the flight ring, and
  the broker-side and pod-side spans share one trace id (one trace
  across the hop).
- **Drain migration under load**: ``POST /v1/migrate {"pod": ...}``
  drains a pod while a tenant is computing — parked residents re-adopt
  on the survivor (resumed bit-identical), the shed queued admission
  spills to the survivor as a fresh submission, and new placements
  route away from the draining pod.
- **Condemn/rejoin + honest Retry-After**: a toggleable stub pod is
  condemned after the miss threshold and rejoins after the healthy
  streak; rejections carry Retry-After from real pod hints when pods
  answered, and from fleet headroom when none could; the client's
  bounded 429 backoff loop (``--retries``) lands the retried submit.
- **Broker restart**: placements are soft state — a fresh broker
  re-discovers residents from the pods' own session lists, and
  ``POST /v1/recover`` re-adopts an orphaned checkpoint no live pod
  claims, resumed exactly to its parked turn.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.serve import (
    GatewayServer,
    ServeConfig,
    ServePlane,
)
from distributed_gol_tpu.serve import wire
from distributed_gol_tpu.serve.broker import (
    Broker,
    BrokerConfig,
    scan_resumable,
)
from distributed_gol_tpu.serve.httpd import StdlibHTTPServer, read_body
from distributed_gol_tpu.serve.podclient import backoff_delay
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
    PodChaos,
)
from tools.gol_client import GatewayError, GolClient

W = H = 32
SUPERSTEP = 4


def spec_doc(turns: int, seed: int, checkpoint_every: int = 0) -> dict:
    """One wire session spec (no tenant key — POST adds it)."""
    params = {
        "width": W, "height": H, "turns": turns, "engine": "roll",
        "superstep": SUPERSTEP, "cycle_check": 0, "ticker_period": 60.0,
    }
    if checkpoint_every:
        params["checkpoint_every_turns"] = checkpoint_every
    return {"params": params, "soup": {"density": 0.3, "seed": seed}}


def submit_via(client: GolClient, tenant: str, spec: dict) -> dict:
    return client._request(
        "POST", "/v1/sessions", {"tenant": tenant, **json.loads(json.dumps(spec))}
    )


def wait_for(predicate, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def broker_state(client: GolClient, tenant: str) -> dict | None:
    """A state poll that tolerates the mid-failover gap (no placement /
    pod unreachable for a beat)."""
    try:
        return client.state(tenant)
    except (GatewayError, OSError):
        return None


def oracle_final(tmp_path: Path, tenant: str, spec: dict) -> np.ndarray:
    """Fault-free oracle: the same spec through an undisturbed plane."""
    params, _ = wire.params_from_spec(
        tenant, json.loads(json.dumps(spec)), root=tmp_path / "oracle-up"
    )
    with ServePlane(
        ServeConfig(max_sessions=1),
        checkpoint_root=tmp_path / f"oracle-{tenant}",
    ) as plane:
        handle = plane.submit(tenant, params)
        assert handle.wait(timeout=120)
        assert handle.status == "completed"
        return np.asarray(handle.final)


def counter(name: str) -> float:
    return (
        obs_metrics.REGISTRY.snapshot().to_dict()["counters"].get(name, 0)
    )


# -- satellite units -----------------------------------------------------------


class TestBackoffDelay:
    def test_pr2_shape(self):
        assert backoff_delay(1, 0.05, 1.0) == pytest.approx(0.05)
        assert backoff_delay(2, 0.05, 1.0) == pytest.approx(0.1)
        assert backoff_delay(3, 0.05, 1.0) == pytest.approx(0.2)

    def test_capped(self):
        assert backoff_delay(30, 0.05, 1.0) == 1.0


class TestPodDownFaultKind:
    def test_schedulable_like_device_down(self):
        plan = FaultPlan.from_json(
            '{"faults": [{"at": 12, "kind": "pod_down", "device": 1}]}'
        )
        (fault,) = plan.faults
        assert (fault.at, fault.kind, fault.device) == (12, "pod_down", 1)

    def test_dispatch_harness_refuses_pod_down(self):
        plan = FaultPlan([Fault(0, "pod_down")])
        with pytest.raises(ValueError, match="pod_down"):
            FaultInjectionBackend(object(), plan)

    def test_chaos_driver_validates_pod_index(self):
        with pytest.raises(ValueError, match="only 1 pod"):
            PodChaos([object()], FaultPlan([Fault(0, "pod_down", device=3)]))

    def test_sigkill_and_partition_against_real_children(self):
        procs = [
            subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])
            for _ in range(2)
        ]
        try:
            chaos = PodChaos(
                procs,
                FaultPlan([
                    Fault(10, "pod_down", device=0),  # SIGKILL
                    Fault(20, "pod_down", device=1, seconds=2.0),  # partition
                ]),
            )
            assert chaos.maybe_fire(5) == []
            struck = chaos.maybe_fire(25)  # both thresholds passed
            assert len(struck) == 2 and chaos.done
            wait_for(lambda: procs[0].poll() is not None, 10, "SIGKILL")
            # The partitioned pod is stopped now and heals afterwards.
            # (Poll, don't one-shot: on a loaded rig the process-table
            # read can land after the SIGCONT timer.)
            wait_for(
                lambda: Path(f"/proc/{procs[1].pid}/stat")
                .read_text().split()[2] == "T",
                10, "partition should SIGSTOP",
            )
            wait_for(
                lambda: Path(f"/proc/{procs[1].pid}/stat")
                .read_text().split()[2] != "T",
                10, "partition heal",
            )
            assert procs[1].poll() is None
            assert [f.at for f, _ in chaos.fired] == [10, 20]
            assert chaos.maybe_fire(99) == []  # nothing left to fire
            chaos.stop()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p.wait(timeout=10)


class TestFleetView:
    def test_render_fleet_rows(self):
        cur = {
            "t": 10.0,
            "health": {
                "broker": True, "ready": True, "pods_ready": 1,
                "pods_condemned": 1, "placements": 2,
                "resident_sessions": 2, "queued_sessions": 1,
                "resident_cells": 2048,
                "pods": [
                    {"endpoint": "http://a:1", "status": "ready",
                     "condemned": False, "resident_sessions": 2,
                     "queued_sessions": 1, "resident_cells": 2048,
                     "effective_total_cells": 4096,
                     "slo_alerting": ["latency"],
                     "placed": ["alice", "bob"]},
                    {"endpoint": "http://b:2", "status": "condemned",
                     "condemned": True, "misses": 2,
                     "resident_sessions": 0, "queued_sessions": 0,
                     "resident_cells": 0},
                ],
            },
        }
        prev = json.loads(json.dumps(cur))
        prev["t"] = 9.0
        prev["health"]["pods"][0]["resident_cells"] = 1024
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
        from pod_top import render_fleet

        out = render_fleet(cur, prev)
        assert "http://a:1" in out and "http://b:2" in out
        assert "condemned(2)" in out
        assert "!latency" in out
        assert "alice,bob" in out
        assert "2,048/4,096 (50%)" in out
        assert "1,024" in out  # cells/s from the two scrapes


class TestBrokerConfigValidation:
    def test_bad_thresholds_refused(self):
        with pytest.raises(ValueError):
            BrokerConfig(probe_miss_threshold=0)
        with pytest.raises(ValueError):
            BrokerConfig(probe_interval_seconds=0)


# -- a toggleable stub pod (condemn/rejoin row; no jax) ------------------------


class StubPod(StdlibHTTPServer):
    """A pod-shaped HTTP server the test scripts: health toggles,
    POST /v1/sessions answers from a scripted queue, session control
    is recorded, and per-tenant state answers from ``state_doc``."""

    thread_name = "gol-stub-pod"

    def __init__(self):
        self.healthy = True
        self.posts = 0
        self.scripted: list[tuple[int, dict]] = []
        self.controls: list[str] = []
        self.state_doc: dict = {"status": "running"}
        super().__init__(port=0)

    def handle(self, request, method, path, query):
        if path == "/healthz" and method == "GET":
            if not self.healthy:
                request._send_json(503, {"error": "down"})
                return True
            request._send_json(200, {
                "ready": True, "live": True, "degraded": False,
                "draining": False, "devices_lost": 0,
                "resident_sessions": 0, "queued_sessions": 0,
                "resident_cells": 0,
                "capacity": {"effective_total_cells": 1_000_000},
                "slo": {"alerting": []}, "tenants": {},
            })
            return True
        if path == "/v1/sessions" and method == "GET":
            request._send_json(200, {"sessions": {}})
            return True
        if path == "/v1/sessions" and method == "POST":
            doc = json.loads(read_body(request) or b"{}")
            self.posts += 1
            code, body = (
                self.scripted.pop(0)
                if self.scripted
                else (201, {"tenant": doc.get("tenant"), "status": "running"})
            )
            headers = []
            if code == 429 and "retry_after" in body:
                headers = [("Retry-After", f"{body['retry_after']:g}")]
            request._send_json(code, body, headers=headers)
            return True
        if path.startswith("/v1/sessions/") and method == "GET":
            request._send_json(200, dict(self.state_doc))
            return True
        if path.startswith("/v1/sessions/") and method == "POST":
            self.controls.append(path.rsplit("/", 1)[-1])
            request._send_json(200, {"ok": True})
            return True
        return False


class TestCondemnRejoin:
    def test_condemned_pod_rejoins_and_retry_after_is_honest(self, tmp_path):
        stub = StubPod()
        config = BrokerConfig(
            probe_interval_seconds=60.0,  # probes are driven by hand
            probe_miss_threshold=2,
            rejoin_threshold=2,
            checkpoint_root=tmp_path,
            retry_after_seconds=1.0,
        )
        broker = Broker([stub.url], config=config)
        client = GolClient(broker.url)
        try:
            broker.probe_once()
            base_condemned = counter("broker.pods_condemned")
            base_rejoined = counter("broker.pods_rejoined")

            # A pod 429 hint propagates verbatim through the broker.
            stub.scripted.append(
                (429, {"error": "shed", "retry_after": 2.5})
            )
            with pytest.raises(GatewayError) as ei:
                submit_via(client, "t1", spec_doc(100, 1))
            assert ei.value.status == 429
            assert ei.value.retry_after == pytest.approx(2.5)

            # The client's bounded backoff loop lands the retried POST.
            stub.scripted.append(
                (429, {"error": "shed", "retry_after": 0.01})
            )
            posts_before = stub.posts
            retrier = GolClient(broker.url, retries=2)
            receipt = submit_via(retrier, "t2", spec_doc(100, 2))
            assert receipt["pod"] == stub.url
            assert stub.posts == posts_before + 2

            # Miss-threshold condemnation mirrors the device blacklist.
            stub.healthy = False
            broker.probe_once()
            broker.probe_once()
            states = broker.pod_states()
            assert states[0]["condemned"] and states[0]["misses"] == 2
            assert counter("broker.pods_condemned") == base_condemned + 1
            kinds = [r["kind"] for r in broker.flight.records()]
            assert "pod_condemned" in kinds
            # With no answering pod the Retry-After hint comes from the
            # fleet's own recovery horizon, not a made-up constant.
            with pytest.raises(GatewayError) as ei:
                submit_via(client, "t3", spec_doc(100, 3))
            assert ei.value.status == 429
            horizon = config.probe_interval_seconds * (
                config.probe_miss_threshold + config.rejoin_threshold
            )
            assert ei.value.retry_after == pytest.approx(
                max(config.retry_after_seconds, horizon)
            )

            # A healthy streak past the threshold rejoins the pod.
            stub.healthy = True
            broker.probe_once()
            assert broker.pod_states()[0]["condemned"]  # streak of 1
            broker.probe_once()
            assert not broker.pod_states()[0]["condemned"]
            assert counter("broker.pods_rejoined") == base_rejoined + 1
            assert "pod_rejoined" in [
                r["kind"] for r in broker.flight.records()
            ]
            receipt = submit_via(client, "t4", spec_doc(100, 4))
            assert receipt["pod"] == stub.url
            assert broker.placement("t4") == stub.url
        finally:
            broker.close()
            stub.close()


class TestPermanentRejectionRelay:
    def test_pod_4xx_relays_verbatim_not_429(self, tmp_path):
        """A pod that REFUSES a spec (409 duplicate, 400 bad spec) is
        a permanent verdict: the broker relays the pod's status and
        body instead of masking it as a retryable 429 — and the
        client's --retries loop therefore does NOT sleep and re-send
        the same doomed spec."""
        stub = StubPod()
        broker = Broker(
            [stub.url],
            BrokerConfig(
                probe_interval_seconds=60.0, checkpoint_root=tmp_path
            ),
        )
        try:
            broker.probe_once()
            stub.scripted.append((409, {"error": "tenant exists"}))
            posts_before = stub.posts
            client = GolClient(broker.url, retries=3)
            with pytest.raises(GatewayError) as ei:
                submit_via(client, "dup", spec_doc(100, 1))
            assert ei.value.status == 409
            assert ei.value.body["error"] == "tenant exists"
            assert ei.value.body["pod"] == stub.url
            assert stub.posts == posts_before + 1, "no client retry loop"
        finally:
            broker.close()
            stub.close()


class TestMigrationGuards:
    def test_migrate_refuses_before_quit_when_no_target(self, tmp_path):
        """With no admitting target in the ring the migrate answers
        503 WITHOUT quitting the source — a healthy session is never
        stopped just to discover the fleet is full."""
        stub = StubPod()
        broker = Broker(
            [stub.url],
            BrokerConfig(
                probe_interval_seconds=60.0, checkpoint_root=tmp_path
            ),
        )
        client = GolClient(broker.url)
        try:
            broker.probe_once()
            assert submit_via(client, "t1", spec_doc(100, 1))
            with pytest.raises(GatewayError) as ei:
                client._request("POST", "/v1/migrate", {"tenant": "t1"})
            assert ei.value.status == 503
            assert stub.controls == [], "source must not be quit"
            assert broker.placement("t1") == stub.url
        finally:
            broker.close()
            stub.close()

    def test_failed_placement_restores_the_source(self, tmp_path):
        """If placement fails AFTER the source was quit (the target
        filled up in the race window), the spec is re-submitted to the
        source — the parked checkpoint resumes where the aborted
        migration stopped it, and the placement stays honest."""
        stub_a, stub_b = StubPod(), StubPod()
        stub_a.state_doc = {"status": "parked", "resumable": True}
        broker = Broker(
            [stub_a.url, stub_b.url],
            BrokerConfig(
                probe_interval_seconds=60.0, checkpoint_root=tmp_path
            ),
        )
        client = GolClient(broker.url)
        try:
            broker.probe_once()
            assert submit_via(client, "t1", spec_doc(100, 1))["pod"] == (
                stub_a.url
            )
            stub_b.scripted.append((503, {"error": "draining"}))
            with pytest.raises(GatewayError) as ei:
                client._request(
                    "POST", "/v1/migrate",
                    {"tenant": "t1", "to": stub_b.url},
                )
            assert ei.value.status == 502
            assert ei.value.body["restored"] is True
            assert stub_a.controls == ["quit"]
            assert stub_a.posts == 2, "initial submit + rollback submit"
            assert broker.placement("t1") == stub_a.url
            assert "migration_failed" in [
                r["kind"] for r in broker.flight.records()
            ]
        finally:
            broker.close()
            stub_a.close()
            stub_b.close()


# -- SIGKILL failover (subprocess pod + survivor) ------------------------------


def start_subprocess_pod(root: Path) -> tuple[subprocess.Popen, str]:
    """A REAL pod process (``serve --gateway-port 0``) on the shared
    checkpoint root; returns (proc, gateway url) once the banner names
    the bound endpoint."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distributed_gol_tpu", "serve",
            "--gateway-port", "0",
            "--checkpoint-root", str(root),
            "--telemetry-sample-seconds", "0.1",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    lines: list[str] = []

    def pump():
        for line in proc.stderr:
            lines.append(line)

    threading.Thread(target=pump, daemon=True).start()
    try:
        url = wait_for(
            lambda: next(
                (
                    ln.split("gateway: ", 1)[1].split("/v1/sessions", 1)[0]
                    for ln in list(lines)
                    if "gateway: " in ln and "/v1/sessions" in ln
                ),
                None,
            ),
            timeout=120,
            what="subprocess pod gateway banner",
        )
    except BaseException:
        proc.kill()
        proc.wait(timeout=10)
        raise
    return proc, url


class TestSigkillFailover:
    def test_pod_sigkill_mid_run_fails_over_bit_identical(self, tmp_path):
        root = tmp_path / "ckpt"
        alice_spec = spec_doc(20_000, seed=5, checkpoint_every=16)
        bob_spec = spec_doc(12_000, seed=9)

        proc, pod_a = start_subprocess_pod(root)
        plane_b = ServePlane(
            ServeConfig(
                max_sessions=4,
                max_total_cells=300_000,  # A's bigger headroom wins placement
                telemetry_sample_seconds=0.1,
            ),
            checkpoint_root=root,
        )
        gw_b = GatewayServer(plane_b, port=0)
        broker = None
        chaos = None
        try:
            # The survivor's own tenant, submitted before the broker
            # exists — discovery must pick it up.
            bob_params, _ = wire.params_from_spec(
                "bob", json.loads(json.dumps(bob_spec)), root=tmp_path / "up"
            )
            bob_handle = plane_b.submit("bob", bob_params)

            base_failovers = counter("broker.failovers")
            base_condemned = counter("broker.pods_condemned")
            broker = Broker(
                [pod_a, gw_b.url],
                BrokerConfig(
                    probe_interval_seconds=0.1,
                    probe_miss_threshold=2,
                    checkpoint_root=root,
                ),
            )
            client = GolClient(broker.url)
            assert broker.placement("bob") == gw_b.url  # re-discovered
            wait_for(
                lambda: all(
                    p["ready"] and p["status"] == "ready"
                    for p in broker.pod_states()
                ),
                30, "both pods probed ready",
            )

            receipt = submit_via(client, "alice", alice_spec)
            assert receipt["pod"] == pod_a, "headroom placement: A first"
            assert receipt["broker_trace_id"]

            # The chaos driver SIGKILLs the pod once alice crosses the
            # scripted turn threshold — mid-run, no drain, no shutdown
            # hooks.
            chaos = PodChaos(
                [proc],
                FaultPlan([Fault(32, "pod_down", device=0)]),
                turn_fn=lambda: (broker_state(client, "alice") or {}).get(
                    "turn"
                ),
            )
            chaos.watch(interval=0.05)
            wait_for(lambda: chaos.done, 60, "scripted SIGKILL")
            (fault, fired_turn) = chaos.fired[0]
            assert fault.kind == "pod_down" and fired_turn >= 32
            wait_for(lambda: proc.poll() is not None, 10, "pod death")

            # Prober condemns; failover re-adopts alice on the survivor.
            wait_for(
                lambda: broker.placement("alice") == gw_b.url,
                60, "failover placement",
            )
            assert counter("broker.pods_condemned") == base_condemned + 1
            assert counter("broker.failovers") == base_failovers + 1
            records = broker.flight.records()
            condemned = [r for r in records if r["kind"] == "pod_condemned"]
            assert condemned and condemned[0]["pod"] == pod_a
            assert "alice" in condemned[0]["stranded"]
            failover = [r for r in records if r["kind"] == "failover"][0]
            assert failover["tenant"] == "alice"
            assert failover["from_pod"] == pod_a
            assert failover["to_pod"] == gw_b.url
            assert failover["checkpoint_turn"] > 0
            assert failover["checkpoint_turn"] % 16 == 0

            st = wait_for(
                lambda: (
                    (s := broker_state(client, "alice"))
                    and s["status"] in ("completed", "failed")
                    and s
                ),
                120, "alice completion on the survivor",
            )
            assert st["status"] == "completed" and st["turn"] == 20_000
            assert st["pod"] == gw_b.url

            # Bit-identical to the fault-free oracle: the resumed run
            # replayed from the newest intact durable checkpoint.
            final = np.asarray(plane_b.handle("alice").final)
            assert np.array_equal(
                final, oracle_final(tmp_path, "alice", alice_spec)
            )

            # The healthy pod's tenant was undisturbed throughout.
            assert bob_handle.wait(timeout=120)
            assert bob_handle.status == "completed"
            assert np.array_equal(
                np.asarray(bob_handle.final),
                oracle_final(tmp_path, "bob", bob_spec),
            )

            # One trace across the hop: the flagged broker-side failover
            # trace and the pod-side request trace share the trace id.
            doc = client._request("GET", "/traces?limit=200")
            same_id = [
                t for t in doc["traces"]
                if t["trace_id"] == failover["trace_id"]
            ]
            names = {
                s["name"] for t in same_id for s in t.get("spans", ())
            }
            assert "gol.broker.place" in names, "broker-side spans retained"
            assert "gol.admission" in names, "pod-side spans share the id"
        finally:
            if chaos is not None:
                chaos.stop()
            if broker is not None:
                broker.close()
            gw_b.close()
            plane_b.close()
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)


# -- SIGSTOP partition heal (the split-brain row) ------------------------------


class TestPartitionHealRejoin:
    def test_sigstop_partition_heals_without_split_brain(self, tmp_path):
        """The nastier cousin of SIGKILL: a SIGSTOP-partitioned pod is
        condemned and its tenant fails over to the survivor — but the
        pod is NOT dead, and on SIGCONT it resumes running the same
        tenant a survivor now owns (two writers on root/<tenant>).
        The broker must quit the stale resident on the healed pod
        BEFORE readmitting it to the ring."""
        root = tmp_path / "ckpt"
        alice_spec = spec_doc(20_000, seed=7, checkpoint_every=16)
        proc, pod_a = start_subprocess_pod(root)
        plane_b = ServePlane(
            ServeConfig(
                max_sessions=4,
                max_total_cells=300_000,  # A's bigger headroom wins
                telemetry_sample_seconds=0.1,
            ),
            checkpoint_root=root,
        )
        gw_b = GatewayServer(plane_b, port=0)
        broker = None
        stopped = False
        try:
            base_rejoined = counter("broker.pods_rejoined")
            base_quits = counter("broker.rejoin_quits")
            broker = Broker(
                [pod_a, gw_b.url],
                BrokerConfig(
                    probe_interval_seconds=0.1,
                    probe_timeout_seconds=0.5,
                    probe_miss_threshold=2,
                    rejoin_threshold=2,
                    checkpoint_root=root,
                ),
            )
            client = GolClient(broker.url)
            wait_for(
                lambda: all(p["ready"] for p in broker.pod_states()),
                30, "both pods probed ready",
            )
            assert submit_via(client, "alice", alice_spec)["pod"] == pod_a
            wait_for(
                lambda: (broker_state(client, "alice") or {}).get("turn", 0)
                >= 32,
                60, "alice past her first durable checkpoints",
            )

            # Partition: the pod freezes but does NOT die — the exact
            # split-brain shape, because it will resume running alice
            # the instant it thaws.
            os.kill(proc.pid, signal.SIGSTOP)
            stopped = True
            wait_for(
                lambda: broker.pod_states()[0]["condemned"],
                30, "partitioned pod condemned",
            )
            wait_for(
                lambda: broker.placement("alice") == gw_b.url,
                60, "failover placement onto the survivor",
            )

            # Heal.  Readmission must be preceded by the reconcile
            # quit of the healed pod's stale alice.
            os.kill(proc.pid, signal.SIGCONT)
            stopped = False
            wait_for(
                lambda: not broker.pod_states()[0]["condemned"],
                30, "pod rejoined after reconcile",
            )
            assert counter("broker.pods_rejoined") == base_rejoined + 1
            assert counter("broker.rejoin_quits") == base_quits + 1
            records = broker.flight.records()
            quit_rec = [
                r for r in records if r["kind"] == "rejoin_quit"
            ][0]
            assert quit_rec["tenant"] == "alice"
            assert quit_rec["pod"] == pod_a
            assert quit_rec["owner"] == gw_b.url
            kinds = [r["kind"] for r in records]
            assert kinds.index("rejoin_quit") < kinds.index("pod_rejoined")

            # One owner: placement still points at the survivor, and
            # the healed pod's stale alice is parked, not computing.
            assert broker.placement("alice") == gw_b.url
            pod_client = GolClient(pod_a)
            wait_for(
                lambda: (
                    pod_client._request("GET", "/v1/sessions")["sessions"]
                    .get("alice", {}).get("status")
                    not in ("running", "queued", "paused")
                ),
                30, "stale alice stopped on the healed pod",
            )

            # The survivor's run is undisturbed by the brief overlap:
            # bit-identical to the fault-free oracle.
            st = wait_for(
                lambda: (
                    (s := broker_state(client, "alice"))
                    and s["status"] in ("completed", "failed")
                    and s
                ),
                120, "alice completion on the survivor",
            )
            assert st["status"] == "completed" and st["turn"] == 20_000
            assert st["pod"] == gw_b.url
            assert np.array_equal(
                np.asarray(plane_b.handle("alice").final),
                oracle_final(tmp_path, "alice", alice_spec),
            )
        finally:
            if stopped:
                os.kill(proc.pid, signal.SIGCONT)
            if broker is not None:
                broker.close()
            gw_b.close()
            plane_b.close()
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)


# -- drain migration under load ------------------------------------------------


class TestDrainMigration:
    def test_pod_drain_migrates_parked_and_spills_queued(self, tmp_path):
        root = tmp_path / "ckpt"
        plane_a = ServePlane(
            ServeConfig(
                max_sessions=2, max_queued=4, telemetry_sample_seconds=0.1
            ),
            checkpoint_root=root,
        )
        gw_a = GatewayServer(plane_a, port=0)
        plane_b = ServePlane(
            ServeConfig(
                max_sessions=4,
                max_total_cells=300_000,
                telemetry_sample_seconds=0.1,
            ),
            checkpoint_root=root,
        )
        gw_b = GatewayServer(plane_b, port=0)
        broker = Broker(
            [gw_a.url, gw_b.url],
            BrokerConfig(
                probe_interval_seconds=0.1,
                probe_miss_threshold=3,
                checkpoint_root=root,
            ),
        )
        client = GolClient(broker.url)
        dave_spec = spec_doc(2_000, seed=11)
        erin_spec = spec_doc(2_000, seed=12)
        try:
            wait_for(
                lambda: all(p["ready"] for p in broker.pod_states()),
                30, "pods probed",
            )
            base_migrations = counter("broker.migrations")
            # carol computes THROUGH the drain (the load); dave parks
            # paused; erin waits in A's admission queue.
            assert submit_via(
                client, "carol", spec_doc(200_000, seed=10)
            )["pod"] == gw_a.url
            assert submit_via(client, "dave", dave_spec)["pod"] == gw_a.url
            wait_for(
                lambda: (broker_state(client, "dave") or {}).get("turn", 0)
                > 0,
                30, "dave progress",
            )
            client.pause("dave")
            erin = submit_via(client, "erin", erin_spec)
            assert erin["pod"] == gw_a.url and erin["status"] == "queued"
            wait_for(
                lambda: (broker_state(client, "carol") or {}).get("turn", 0)
                > 0,
                30, "carol progress",
            )

            out = client._request("POST", "/v1/migrate", {"pod": gw_a.url})
            assert out["migrated"] == ["carol", "dave"]
            assert out["spilled"] == ["erin"]
            assert out["lost"] == []
            for tenant in ("carol", "dave", "erin"):
                assert broker.placement(tenant) == gw_b.url
            assert counter("broker.migrations") == base_migrations + 3
            records = broker.flight.records()
            kinds = [
                r["kind"] for r in records
                if r["kind"] in ("migration", "spill")
            ]
            assert sorted(kinds) == ["migration", "migration", "spill"]
            spill = [r for r in records if r["kind"] == "spill"][0]
            assert spill["tenant"] == "erin"
            carol_rec = [
                r for r in records
                if r["kind"] == "migration" and r["tenant"] == "carol"
            ][0]
            assert carol_rec["turn"] > 0  # drained mid-compute

            # The drained pod routes away once the next probe sees it.
            wait_for(
                lambda: broker.pod_states()[0]["status"] == "draining",
                30, "probe observes the drained pod",
            )
            frank = submit_via(client, "frank", spec_doc(400, seed=13))
            assert frank["pod"] == gw_b.url

            # Migrated sessions finish on B, bit-identical to fault-free
            # oracles; the under-load tenant keeps computing past its
            # drain turn.
            for tenant, spec in (("dave", dave_spec), ("erin", erin_spec)):
                st = wait_for(
                    lambda t=tenant: (
                        (s := broker_state(client, t))
                        and s["status"] == "completed"
                        and s
                    ),
                    120, f"{tenant} completion on B",
                )
                assert st["turn"] == 2_000
                assert np.array_equal(
                    np.asarray(plane_b.handle(tenant).final),
                    oracle_final(tmp_path, tenant, spec),
                )
            wait_for(
                lambda: (broker_state(client, "carol") or {}).get("turn", 0)
                > carol_rec["turn"],
                60, "carol computing again on B",
            )
            client.quit("carol")
        finally:
            broker.close()
            gw_a.close()
            gw_b.close()
            plane_a.close()
            plane_b.close()


# -- broker restart re-discovery + orphan recovery -----------------------------


class TestBrokerRestart:
    def test_restarted_broker_rediscovers_and_recovers_orphans(
        self, tmp_path
    ):
        root = tmp_path / "ckpt"
        plane_a = ServePlane(
            ServeConfig(max_sessions=4, telemetry_sample_seconds=0.1),
            checkpoint_root=root,
        )
        gw_a = GatewayServer(plane_a, port=0)
        cfg = BrokerConfig(
            probe_interval_seconds=0.1,
            probe_miss_threshold=3,
            checkpoint_root=root,
        )
        broker1 = Broker([gw_a.url], cfg)
        client1 = GolClient(broker1.url)
        oscar_spec = spec_doc(200_000, seed=21, checkpoint_every=16)
        try:
            wait_for(
                lambda: all(p["ready"] for p in broker1.pod_states()),
                30, "pod probed",
            )
            submit_via(client1, "tina", spec_doc(200_000, seed=20))
            wait_for(
                lambda: (broker_state(client1, "tina") or {}).get("turn", 0)
                > 0,
                30, "tina progress",
            )
        finally:
            broker1.close()  # the broker dies; the pod keeps computing

        # An orphan: a second pod parks a resumable checkpoint on the
        # shared root and is gone before any broker sees it.
        oscar_params, _ = wire.params_from_spec(
            "oscar", json.loads(json.dumps(oscar_spec)), root=tmp_path / "up"
        )
        with ServePlane(
            ServeConfig(max_sessions=2), checkpoint_root=root
        ) as plane_c:
            plane_c.submit("oscar", oscar_params)
            wait_for(
                lambda: (plane_c.handle("oscar").last_turn or 0) > 32,
                60, "oscar progress",
            )
            receipt = plane_c.drain(timeout=60)
            assert receipt["oscar"]["resumable"]
        parked = scan_resumable(root)["oscar"]
        assert parked["turn"] > 0

        base_failovers = counter("broker.failovers")
        broker2 = Broker([gw_a.url], cfg)
        client2 = GolClient(broker2.url)
        try:
            # Soft state rebuilt from the pod's own session list.
            assert broker2.placement("tina") == gw_a.url
            assert "discover" in [
                r["kind"] for r in broker2.flight.records()
            ]
            wait_for(
                lambda: all(p["ready"] for p in broker2.pod_states()),
                30, "restarted broker probes the pod",
            )

            out = client2._request("POST", "/v1/recover", {})
            assert out["adopted"] == ["oscar"] and out["lost"] == []
            assert broker2.placement("oscar") == gw_a.url
            assert counter("broker.failovers") == base_failovers + 1
            failover = [
                r for r in broker2.flight.records()
                if r["kind"] == "failover"
            ][0]
            assert failover["from_pod"] is None
            assert failover["checkpoint_turn"] == parked["turn"]

            # The sidecar-reconstructed spec resumes to EXACTLY the
            # parked turn: no lost work, no invented work — and the
            # board is bit-identical to a fault-free run to that turn.
            st = wait_for(
                lambda: (
                    (s := broker_state(client2, "oscar"))
                    and s["status"] == "completed"
                    and s
                ),
                120, "oscar re-adopted to the parked turn",
            )
            assert st["turn"] == parked["turn"]
            to_turn = json.loads(json.dumps(oscar_spec))
            to_turn["params"]["turns"] = parked["turn"]
            assert np.array_equal(
                np.asarray(plane_a.handle("oscar").final),
                oracle_final(tmp_path, "oscar", to_turn),
            )
            client2.quit("tina")
        finally:
            broker2.close()
            gw_a.close()
            plane_a.close()
