"""Port of the reference's TestSdl/TestMain (sdl_test.go): the viewer-facing
event-ordering contract.

Contract (gol/event.go:55-58, sdl_test.go:58,107-116): a shadow board built
ONLY from CellFlipped XORs must be consistent at every TurnComplete — its
alive count equals the golden count for that turn — and all of a turn's
flips arrive before its TurnComplete.  The reference checks 512²×100; we
check 64²×100 per-cell (same contract, hermetic-friendly) plus the batch
flip extension.
"""

import csv
import queue

import numpy as np

import distributed_gol_tpu as gol


def golden_counts(golden_alive, size):
    with open(golden_alive / f"{size}x{size}.csv") as f:
        return {int(t): int(c) for t, c in list(csv.reader(f))[1:]}


def run_viewer_mode(size, turns, tmp_path, input_images, flip_events):
    params = gol.Params(
        turns=turns,
        image_width=size,
        image_height=size,
        images_dir=input_images,
        out_dir=tmp_path,
        no_vis=False,
        flip_events=flip_events,
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    out = []
    while (e := events.get(timeout=60)) is not None:
        out.append(e)
    return out


def check_shadow_board(events, size, counts, turns):
    """Replays the stream exactly like the reference's replica SDL loop:
    XOR flips into a shadow board, check the count at every TurnComplete."""
    shadow = np.zeros((size, size), dtype=np.uint8)
    turns_seen = 0
    for e in events:
        if isinstance(e, gol.CellFlipped):
            shadow[e.cell.y, e.cell.x] ^= 255
        elif isinstance(e, gol.CellsFlipped):
            for c in e.cells:
                shadow[c.y, c.x] ^= 255
        elif isinstance(e, gol.TurnComplete):
            turns_seen += 1
            assert e.completed_turns == turns_seen, "TurnComplete out of order"
            got = int(np.count_nonzero(shadow))
            assert got == counts[e.completed_turns], (
                f"shadow board count {got} != golden "
                f"{counts[e.completed_turns]} at turn {e.completed_turns}"
            )
        elif isinstance(e, gol.FinalTurnComplete):
            final_alive = {(c.x, c.y) for c in e.alive}
            from_shadow = {
                (int(x), int(y)) for y, x in zip(*np.nonzero(shadow))
            }
            assert final_alive == from_shadow, "final alive set != shadow board"
    assert turns_seen == turns


def test_per_cell_flip_contract(tmp_path, input_images, golden_alive):
    events = run_viewer_mode(64, 100, tmp_path, input_images, "cell")
    check_shadow_board(events, 64, golden_counts(golden_alive, 64), 100)


def test_batch_flip_contract(tmp_path, input_images, golden_alive):
    events = run_viewer_mode(64, 100, tmp_path, input_images, "batch")
    check_shadow_board(events, 64, golden_counts(golden_alive, 64), 100)


def test_flips_512_smoke(tmp_path, input_images, golden_alive):
    """The reference's actual size, batch mode for speed, fewer turns."""
    events = run_viewer_mode(512, 10, tmp_path, input_images, "batch")
    check_shadow_board(events, 512, golden_counts(golden_alive, 512), 10)
