"""Region-of-interest frame plane (ISSUE 11): viewport fetch bit-identity
vs the full-frame crop oracle across engines × meshes × rect kinds, the
delta wire format (encode == apply, reconstruction equals dense frames
over a soup run), the per-stripe activity bitmap, the viewport-aware
auto-stride probe, and the FramePlane fan-out economics (one device
fetch per published turn for any subscriber count)."""

import os
import queue

import numpy as np
import pytest

os.environ.setdefault("SDL_VIDEODRIVER", "dummy")

import jax.numpy as jnp

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine import frames as frames_lib
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import (
    FinalTurnComplete,
    FrameDelta,
    FrameReady,
)
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.pgm import write_pgm
from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops import stencil
from distributed_gol_tpu.serve.frames import FramePlane, _cyclic_bound


def soup(h, w, density=0.25, seed=11):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


def crop(board, rect):
    """The toroidal crop oracle every identity test compares against."""
    y0, x0, vh, vw = rect
    h, w = board.shape
    rows = (np.arange(vh) + y0) % h
    cols = (np.arange(vw) + x0) % w
    return board[rows[:, None], cols[None, :]]


class TestViewportOp:
    def test_matches_oracle_every_wrap_kind(self):
        b = soup(96, 64, seed=1)
        jb = jnp.asarray(b)
        for rect in [
            (10, 10, 20, 20),  # interior
            (90, 10, 20, 20),  # wraps y
            (10, 60, 20, 20),  # wraps x
            (90, 60, 20, 20),  # wraps both
            (-5, -7, 20, 20),  # negative anchors wrap too
            (0, 0, 96, 64),  # the whole board
        ]:
            got = np.asarray(
                stencil.viewport(jb, rect[0], rect[1], rect[2], rect[3])
            )
            assert np.array_equal(got, crop(b, rect)), rect

    def test_dynamic_anchor_shares_one_compilation(self):
        # Pan must not recompile: the jit specialises on SIZE only.
        b = jnp.asarray(soup(64, 64))
        f = stencil.viewport
        first = np.asarray(f(b, 0, 0, 16, 16))
        panned = np.asarray(f(b, 7, 9, 16, 16))
        assert first.shape == panned.shape == (16, 16)
        # Same underlying compiled callable across anchors is implied by
        # static_argnames; the behavioural check is the oracle above.


# Engine × mesh matrix for the Backend-seam identity tests; the
# pallas-packed rows run interpret mode hermetically (conftest pins CPU).
_CONFIGS = [
    ("roll", (1, 1)),
    ("packed", (1, 1)),
    ("pallas-packed", (1, 1)),
    ("roll", (2, 1)),
    ("packed", (2, 1)),
    ("pallas-packed", (2, 1)),
    # Column-sharded rows (round 7): the 2-D tile tier behind the same
    # fetch seam — rects below cross the column seam at W/2 too.
    ("packed", (2, 2)),
    ("pallas-packed", (2, 2)),
]


class TestBackendFetchViewport:
    # Rect kinds: interior, toroidal-wrap (both axes), one that
    # straddles the (2,1)-mesh shard boundary at H/2, and one that
    # straddles BOTH shard seams of a (2,2) mesh of 256².
    _RECTS = [
        (10, 40, 48, 64),
        (230, 230, 48, 64),
        (104, 0, 48, 64),  # straddles row 128 on a (2,1) mesh of 256 rows
        (104, 100, 48, 64),  # straddles row 128 AND column 128 on (2,2)
    ]

    @pytest.mark.parametrize("engine,mesh", _CONFIGS)
    def test_identity_vs_full_fetch_crop(self, engine, mesh):
        size = 256
        b = soup(size, size, seed=5)
        p = Params(
            image_width=size,
            image_height=size,
            turns=10,
            engine=engine,
            mesh_shape=mesh,
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        dev, _ = be.run_turns(dev, 4)
        full = be.fetch(dev)
        for rect in self._RECTS:
            got = be.fetch_viewport(dev, rect)
            assert np.array_equal(got, crop(full, rect)), (engine, mesh, rect)

    def test_fused_viewport_frame_matches_crop(self):
        size = 256
        b = soup(size, size, seed=6)
        p = Params(
            image_width=size, image_height=size, turns=10, engine="roll",
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        rect = (240, 240, 64, 64)  # wraps both axes
        nb, count, frame = be.run_turn_with_viewport(dev, rect, 1, 1, 3)
        full = be.fetch(nb)
        assert count == int(np.count_nonzero(full))
        assert np.array_equal(frame, (crop(full, rect) != 0) * np.uint8(255))

    def test_rect_must_fit_board(self):
        p = Params(image_width=64, image_height=64, turns=1, metrics=False)
        be = Backend(p)
        dev = be.put(soup(64, 64))
        with pytest.raises(ValueError, match="does not fit"):
            be.fetch_viewport(dev, (0, 0, 65, 10))


class TestActivityBitmap:
    def _adaptive_backend(self):
        # A tiled adaptive board: W % 4096 == 0, cap 64 -> 4 stripes of
        # 64 rows; glider in stripe 1, ash elsewhere.
        H, W = 256, 4096
        b = np.zeros((H, W), np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[100 + dy, 600 + dx] = 255
        b[10:12, 50:52] = 255  # still life in stripe 0
        p = Params(
            image_width=W,
            image_height=H,
            turns=10**6,
            engine="pallas-packed",
            skip_stable=True,
            skip_tile_cap=64,
            metrics=False,
        )
        # The small test board is dual-eligible (VMEM-resident AND
        # tiled); the explicit skip_stable trade is announced — scoped
        # here, the adaptive telemetry is exactly what the test wants.
        with pytest.warns(UserWarning, match="forces the tiled kernel"):
            be = Backend(p)
        return be, b

    def test_bitmap_marks_active_stripes_only(self):
        be, b = self._adaptive_backend()
        assert be.activity_bitmap() is None  # nothing resolved yet
        dev = be.put(b)
        for _ in range(3):  # the 2-dispatch safety lag needs 3 dispatches
            dev, _ = be.run_turns(dev, 36)
        bm = be.activity_bitmap()
        assert bm is not None and bm.dtype == bool and bm.shape == (4,)
        assert bm[1], "the glider's stripe must read active"
        assert not bm[0], "still-life stripe must read inactive"
        assert not bm[3], "empty stripe must read inactive"
        assert be.activity_tile_rows() == 64

    def test_bitmap_none_without_adaptive_telemetry(self):
        p = Params(
            image_width=128, image_height=128, turns=10, engine="roll",
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(soup(128, 128))
        for _ in range(3):
            dev, _ = be.run_turns(dev, 5)
        assert be.activity_bitmap() is None
        assert be.activity_tile_rows() is None

    def test_active_tiles_gauge_published(self):
        from distributed_gol_tpu.obs import metrics as obs_metrics

        H, W = 256, 4096
        b = np.zeros((H, W), np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[100 + dy, 600 + dx] = 255
        p = Params(
            image_width=W,
            image_height=H,
            turns=10**6,
            engine="pallas-packed",
            skip_stable=True,
            skip_tile_cap=64,
        )
        with pytest.warns(UserWarning, match="forces the tiled kernel"):
            be = Backend(p)
        dev = be.put(b)
        for _ in range(3):
            dev, _ = be.run_turns(dev, 36)
        snap = obs_metrics.REGISTRY.snapshot().to_dict()
        assert snap["gauges"].get("backend.active_tiles") == 1.0
        assert "backend.skip_fraction" in snap["gauges"]

    def test_sharded_2d_bitmap_and_viewport_are_exact(self):
        """Round-7 row (ISSUE 13): on a column-sharded (2, 2) board the
        activity bitmap assembles board-global over BOTH mesh axes (a
        stripe is active iff any of its column tiles is) and
        ``stencil.viewport`` through the Backend seam stays exact on
        rects crossing the column seam."""
        H, W = 256, 8192
        b = np.zeros((H, W), np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[200 + dy, 600 + dx] = 255  # glider: stripe 3, x-tile 0
        p = Params(
            image_width=W,
            image_height=H,
            turns=10**6,
            engine="pallas-packed",
            mesh_shape=(2, 2),
            skip_stable=True,
            skip_tile_cap=64,
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        for _ in range(3):
            dev, _ = be.run_turns(dev, 36)
        bm = be.activity_bitmap()
        assert bm is not None and bm.ndim == 1 and bm.shape == (4,)
        rows = be.activity_tile_rows()
        assert rows == 64
        assert bm[200 // rows]
        assert not bm[0]
        # Viewport exactness across the column seam at W/2.
        full = be.fetch(dev)
        for rect in [(190, 580, 32, 64), (100, 4080, 48, 64), (250, 8180, 32, 32)]:
            got = be.fetch_viewport(dev, rect)
            assert np.array_equal(got, crop(full, rect)), rect

    def test_sharded_bitmap_is_board_global(self):
        H, W = 4096, 4096
        b = np.zeros((H, W), np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[3000 + dy, 600 + dx] = 255
        p = Params(
            image_width=W,
            image_height=H,
            turns=10**6,
            engine="pallas-packed",
            mesh_shape=(2, 1),
            skip_stable=True,
            skip_tile_cap=512,
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        for _ in range(3):
            dev, _ = be.run_turns(dev, 36)
        bm = be.activity_bitmap()
        assert bm is not None and bm.shape == (8,)  # 2 devices x 4 stripes
        rows = be.activity_tile_rows()
        assert rows == 512
        # The glider lives near row 3000 -> stripe 5 (device 1, local 1).
        assert bm[3000 // rows]
        assert not bm[0]


class TestDeltaCodec:
    def test_bands_roundtrip_and_untouched_rows(self):
        prev = soup(64, 40, seed=2)
        new = prev.copy()
        new[17, 3] ^= 255
        new[40:44, 10:20] ^= 255
        bands = frames_lib.delta_bands(prev, new)
        ys = [y for y, _ in bands]
        assert ys == [16, 40], "8-row bands covering exactly the changes"
        buf = prev.copy()
        frames_lib.apply_bands(buf, bands)
        assert np.array_equal(buf, new)

    def test_identical_frames_empty_delta(self):
        f = soup(32, 32, seed=3)
        assert frames_lib.delta_bands(f, f.copy()) == ()

    def test_window_applies_deltas_in_place_without_touching_others(self):
        pytest.importorskip("pygame")
        from distributed_gol_tpu.viewer.window import Window

        w = Window(32, 32)
        try:
            base = np.zeros((32, 32), np.uint8)
            w.set_frame(base)
            # Poison the buffer rows OUTSIDE the band with a sentinel the
            # engine never produces; an apply that rewrites the whole
            # frame (the round-5 set_frame path) would erase it.
            w._pixels[:] = 7
            rows = np.full((8, 32), 255, np.uint8)
            w.apply_delta(((8, rows),))
            assert np.array_equal(w._pixels[8:16], rows)
            assert (w._pixels[:8] == 7).all() and (w._pixels[16:] == 7).all(), (
                "unchanged-tile rows must not be touched"
            )
            # set_frame must COPY: mutating the window buffer afterwards
            # must not reach back into the producer's array.
            w.set_frame(base)
            w._pixels[0, 0] = 99
            assert base[0, 0] == 0
        finally:
            w.destroy()


class TestROIViewerRun:
    """The 200-turn soup proof: the delta stream reconstructs frames
    bit-identical to the dense crop oracle at every rendered turn."""

    @pytest.mark.slow
    def test_delta_stream_reconstructs_dense_frames_200_turns(
        self, tmp_path
    ):
        self._roi_run(tmp_path, turns=200)

    def test_delta_stream_reconstructs_dense_frames(self, tmp_path):
        # The tier-1-sized form of the 200-turn soup proof (same code
        # path, shorter run).
        self._roi_run(tmp_path, turns=40)

    def _roi_run(self, tmp_path, turns):
        img = tmp_path / "images"
        img.mkdir()
        size = 128
        board = soup(size, size, seed=9)
        write_pgm(img / f"{size}x{size}.pgm", board)
        rect = (100, 100, 64, 64)  # wraps both axes
        p = Params(
            turns=turns,
            image_width=size,
            image_height=size,
            no_vis=False,
            viewport=rect,
            frame_stride=1,
            images_dir=img,
            out_dir=tmp_path,
            engine="roll",
            metrics=False,
        )
        assert p.wants_frames() and p.frame_deltas_enabled()
        ev = queue.Queue()
        gol.run(p, ev)
        # Oracle: independent roll-stencil evolution + toroidal crop.
        table = jnp.asarray(CONWAY.table)
        b = jnp.asarray(board)
        oracle = {0: board}
        for t in range(1, turns + 1):
            b = stencil.step(b, table)
            oracle[t] = np.asarray(b)
        buf = None
        frames = []
        deltas = keyframes = 0
        while True:
            e = ev.get()
            if e is None:
                break
            if isinstance(e, FrameReady):
                keyframes += 1
                buf = np.array(e.frame, copy=True)
                assert e.rect == rect
                frames.append((e.completed_turns, buf.copy()))
            elif isinstance(e, FrameDelta):
                deltas += 1
                frames_lib.apply_bands(buf, e.bands)
                frames.append((e.completed_turns, buf.copy()))
        assert len(frames) == turns + 1  # initial keyframe + one per turn
        assert keyframes == 2 and deltas == turns - 1
        for t, f in frames:
            want = (crop(oracle[t], rect) != 0) * np.uint8(255)
            assert np.array_equal(f, want), f"turn {t}"

    def test_full_board_frame_stream_unchanged_without_viewport(
        self, tmp_path
    ):
        # No viewport => deltas stay off and the stream is the round-5
        # FrameReady-per-turn contract, byte for byte.
        img = tmp_path / "images"
        img.mkdir()
        size = 2048  # above _FLIP_VIEW_MAX_CELLS => frame mode
        board = np.zeros((size, size), np.uint8)
        board[0:2, 0:2] = 255
        write_pgm(img / f"{size}x{size}.pgm", board)
        p = Params(
            turns=3,
            image_width=size,
            image_height=size,
            no_vis=False,
            view_mode="frame",
            frame_stride=1,
            images_dir=img,
            out_dir=tmp_path,
            engine="roll",
            metrics=False,
        )
        assert not p.frame_deltas_enabled()
        ev = queue.Queue()
        gol.run(p, ev)
        kinds = []
        while True:
            e = ev.get()
            if e is None:
                break
            kinds.append(type(e).__name__)
        assert "FrameDelta" not in kinds
        assert kinds.count("FrameReady") == 4  # initial + one per turn


class TestViewportStrideProbe:
    def test_probe_measures_viewport_fetch_path(self, tmp_path, monkeypatch):
        """ISSUE 11 satellite: with ROI frames the auto-stride probe must
        time the viewport-rect fetch, not the full-board pool."""
        img = tmp_path / "images"
        img.mkdir()
        size = 128
        write_pgm(img / f"{size}x{size}.pgm", soup(size, size, seed=4))
        rect = (0, 0, 64, 64)
        p = Params(
            turns=4,
            image_width=size,
            image_height=size,
            no_vis=False,
            viewport=rect,
            frame_stride=0,  # latency-adaptive: the probe runs
            images_dir=img,
            out_dir=tmp_path,
            engine="roll",
            metrics=False,
        )
        probed = []
        orig = Backend.probe_frame_fetch

        def spy(self, board, fy, fx, rect=None):
            probed.append(rect)
            return orig(self, board, fy, fx, rect=rect)

        monkeypatch.setattr(Backend, "probe_frame_fetch", spy)
        ev = queue.Queue()
        gol.run(p, ev)
        while ev.get() is not None:
            pass
        assert probed, "auto-stride must probe at viewer start"
        assert all(r == rect for r in probed), (
            "every probe must measure the viewport fetch path"
        )

    def test_zoom_reprobes_materially_resized_viewport(
        self, tmp_path, monkeypatch
    ):
        img = tmp_path / "images"
        img.mkdir()
        size = 128
        write_pgm(img / f"{size}x{size}.pgm", soup(size, size, seed=4))
        p = Params(
            turns=8,
            image_width=size,
            image_height=size,
            no_vis=False,
            viewport=(0, 0, 64, 64),
            frame_stride=0,
            images_dir=img,
            out_dir=tmp_path,
            engine="roll",
            metrics=False,
        )
        probed = []
        orig = Backend.probe_frame_fetch

        def spy(self, board, fy, fx, rect=None):
            probed.append(rect)
            return orig(self, board, fy, fx, rect=rect)

        monkeypatch.setattr(Backend, "probe_frame_fetch", spy)
        keys = queue.Queue()
        keys.put("+")  # zoom in: 64x64 -> 32x32, a 4x area change
        ev = queue.Queue()
        gol.run(p, ev, key_presses=keys)
        while ev.get() is not None:
            pass
        sizes = {(r[2], r[3]) for r in probed}
        assert (64, 64) in sizes, "the starting viewport was probed"
        assert (32, 32) in sizes, (
            "a material zoom must re-probe the new viewport size"
        )

    def test_pan_zoom_arithmetic(self):
        from distributed_gol_tpu.engine.controller import Controller

        p = Params(
            image_width=256,
            image_height=256,
            turns=1,
            no_vis=False,
            viewport=(0, 0, 64, 64),
            engine="roll",
            metrics=False,
        )
        c = Controller(p, queue.Queue())
        c._pan_zoom("d")
        assert c._rect == [0, 32, 64, 64] and c._frame_keyframe
        c._pan_zoom("x")
        assert c._rect == [32, 32, 64, 64]
        c._pan_zoom("a")
        c._pan_zoom("w")
        assert c._rect == [0, 0, 64, 64]
        c._pan_zoom("w")  # wraps the torus
        assert c._rect == [224, 0, 64, 64]
        c._rect = [0, 0, 64, 64]
        c._pan_zoom("+")
        assert c._rect == [16, 16, 32, 32] and c._rect_resized
        c._pan_zoom("-")
        assert c._rect == [0, 0, 64, 64]
        c._pan_zoom("-")  # zoom out clamps at the board
        c._pan_zoom("-")
        assert c._rect[2:] == [256, 256]
        # Zoom-in floor (review finding): '+' never GROWS a sub-16 rect
        # and never mints a rect larger than a small board.
        c._rect = [0, 0, 8, 8]
        c._pan_zoom("+")
        assert c._rect[2:] == [8, 8]
        p_small = Params(
            image_width=8,
            image_height=8,
            turns=1,
            no_vis=False,
            viewport=(0, 0, 8, 8),
            engine="roll",
            metrics=False,
        )
        cs = Controller(p_small, queue.Queue())
        cs._pan_zoom("+")
        assert cs._rect[2:] == [8, 8], "zoom must not exceed the board"


class TestCyclicBound:
    def test_interior_union(self):
        assert _cyclic_bound([(10, 20), (40, 10)], 100) == (10, 40)

    def test_wrapping_union_shorter_than_interior(self):
        # Rects at both edges: the wrap-crossing window is shortest.
        y0, ext = _cyclic_bound([(90, 8), (2, 8)], 100)
        assert (y0, ext) == (90, 20)

    def test_spread_covers_with_one_window(self):
        # Three spread rects: one 70-row window still covers them all.
        assert _cyclic_bound([(0, 10), (30, 10), (60, 10)], 90) == (0, 70)

    def test_spread_degrades_to_full_axis(self):
        # No window shorter than the ring covers these; one full-axis
        # fetch (still ONE fetch, never two) is the degradation.
        assert _cyclic_bound([(0, 30), (30, 30), (60, 30)], 90) == (0, 90)

    def test_single(self):
        assert _cyclic_bound([(95, 10)], 100) == (95, 10)


class TestFramePlaneFanOut:
    def _serve(self, n_subs, turns=4, size=256, seed=13):
        from distributed_gol_tpu.obs import metrics as obs_metrics

        rng = np.random.default_rng(seed)
        b = soup(size, size, seed=seed)
        p = Params(
            image_width=size, image_height=size, turns=10, engine="roll",
        )
        be = Backend(p)
        dev = be.put(b)
        plane = FramePlane(board_shape=(size, size))
        subs = [
            plane.subscribe(
                (
                    int(rng.integers(0, size)),
                    int(rng.integers(0, size)),
                    64,
                    64,
                ),
                maxsize=turns + 1,
            )
            for _ in range(n_subs)
        ]
        reg = obs_metrics.REGISTRY
        fetches0 = reg.counter("frames.fetches").value
        for turn in range(1, turns + 1):
            dev, _ = be.run_turns(dev, 1)
            stats = plane.publish(turn, lambda r: be.fetch_viewport(dev, r))
            assert stats["subscribers"] == n_subs
        fetches = reg.counter("frames.fetches").value - fetches0
        return be, dev, subs, fetches, turns

    @pytest.mark.parametrize("n_subs", [1, 8, 32])
    def test_one_fetch_per_frame_any_subscriber_count(self, n_subs):
        be, dev, subs, fetches, turns = self._serve(n_subs)
        assert fetches == turns, "fetches/frame == 1 regardless of N"
        full = be.fetch(dev)
        size = full.shape[0]
        for s in subs:
            got = s.reconstruct()
            want = (crop(full, s.rect) != 0) * np.uint8(255)
            assert np.array_equal(got, want)

    def test_mid_stream_viewport_change_rekeyframes(self):
        size = 128
        b = soup(size, size, seed=21)
        p = Params(
            image_width=size, image_height=size, turns=10, engine="roll",
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        plane = FramePlane(board_shape=(size, size))
        sub = plane.subscribe((0, 0, 32, 32), maxsize=16)
        plane.publish(1, lambda r: be.fetch_viewport(dev, r))
        plane.set_viewport(sub, (50, 50, 48, 48))
        plane.publish(2, lambda r: be.fetch_viewport(dev, r))
        evs = []
        while True:
            try:
                evs.append(sub.events.get_nowait())
            except queue.Empty:
                break
        assert [type(e).__name__ for e in evs] == ["FrameReady", "FrameReady"]
        full = be.fetch(dev)
        want = (crop(full, (50, 50, 48, 48)) != 0) * np.uint8(255)
        assert np.array_equal(np.asarray(evs[-1].frame), want)

    def test_slow_subscriber_drops_oldest_then_rekeyframes(self):
        size = 64
        b = soup(size, size, seed=22)
        p = Params(
            image_width=size, image_height=size, turns=64, engine="roll",
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        plane = FramePlane(board_shape=(size, size))
        sub = plane.subscribe((0, 0, 32, 32), maxsize=2)
        for turn in range(1, 8):
            dev, _ = be.run_turns(dev, 1)
            plane.publish(turn, lambda r: be.fetch_viewport(dev, r))
        # The consumer fell 5 frames behind; reconstruction must still
        # converge because a drop forces the next ship to keyframe.
        got = sub.reconstruct()
        full = be.fetch(dev)
        want = (crop(full, sub.rect) != 0) * np.uint8(255)
        assert np.array_equal(got, want)

    def test_reconstruct_skips_deltas_whose_keyframe_was_evicted(self):
        # Drop-oldest can evict the anchoring keyframe while its deltas
        # survive; reconstruct must skip the orphans (review finding),
        # not crash applying bands to a None buffer.
        size = 64
        b = soup(size, size, seed=24)
        p = Params(
            image_width=size, image_height=size, turns=64, engine="roll",
            metrics=False,
        )
        be = Backend(p)
        dev = be.put(b)
        plane = FramePlane(board_shape=(size, size))
        sub = plane.subscribe((0, 0, 32, 32), maxsize=3)
        for turn in range(1, 6):
            dev, _ = be.run_turns(dev, 1)
            plane.publish(turn, lambda r: be.fetch_viewport(dev, r))
        # maxsize 3 with 5 ships: the turn-1 keyframe was evicted and the
        # queue leads with orphan deltas; the post-drop re-keyframe then
        # converges the stream.
        got = sub.reconstruct()
        full = be.fetch(dev)
        want = (crop(full, sub.rect) != 0) * np.uint8(255)
        assert np.array_equal(got, want)

    def test_unbound_publish_refuses(self):
        plane = FramePlane()
        plane.subscribe((0, 0, 8, 8))
        with pytest.raises(ValueError, match="unbound"):
            plane.publish(1, lambda r: np.zeros((8, 8), np.uint8))

    def test_controller_attached_plane_publishes_each_rendered_turn(
        self, tmp_path
    ):
        from distributed_gol_tpu.obs import metrics as obs_metrics

        img = tmp_path / "images"
        img.mkdir()
        size = 128
        board = soup(size, size, seed=23)
        write_pgm(img / f"{size}x{size}.pgm", board)
        p = Params(
            turns=5,
            image_width=size,
            image_height=size,
            no_vis=False,
            viewport=(0, 0, 64, 64),
            frame_stride=1,
            images_dir=img,
            out_dir=tmp_path,
            engine="roll",
        )
        plane = FramePlane()
        subs = [plane.subscribe((i * 16, i * 8, 32, 32), maxsize=8) for i in range(3)]
        reg = obs_metrics.REGISTRY
        fetches0 = reg.counter("frames.fetches").value
        ev = queue.Queue()
        gol.run(p, ev, frame_plane=plane)
        final = None
        while True:
            e = ev.get()
            if e is None:
                break
            if isinstance(e, FinalTurnComplete):
                final = e
        assert final is not None and final.completed_turns == 5
        assert reg.counter("frames.fetches").value - fetches0 == 5
        # Every spectator's reconstruction equals the final board's crop.
        final_np = np.zeros((size, size), np.uint8)
        for c in final.alive:
            final_np[c.y, c.x] = 255
        for s in subs:
            got = s.reconstruct()
            want = (crop(final_np, s.rect) != 0) * np.uint8(255)
            assert np.array_equal(got, want)


class TestParamsViewport:
    def test_viewport_forces_frame_mode_any_board_size(self):
        p = Params(
            image_width=512, image_height=512, no_vis=False,
            viewport=(0, 0, 128, 128),
        )
        assert p.wants_frames() and not p.wants_flips()
        assert p.frame_factors() == (1, 1)  # viewport fits frame_max

    def test_viewport_validation(self):
        with pytest.raises(ValueError, match="does not fit"):
            Params(image_width=64, image_height=64, viewport=(0, 0, 65, 64))
        with pytest.raises(ValueError, match="y0, x0"):
            Params(image_width=64, image_height=64, viewport=(0, 0, 64))

    def test_frame_deltas_resolution(self):
        assert not Params().frame_deltas_enabled()
        assert Params(
            image_width=64, image_height=64, viewport=(0, 0, 32, 32)
        ).frame_deltas_enabled()
        assert Params(frame_deltas=True).frame_deltas_enabled()
        assert not Params(
            image_width=64,
            image_height=64,
            viewport=(0, 0, 32, 32),
            frame_deltas=False,
        ).frame_deltas_enabled()

    def test_viewport_pooling_factors(self):
        p = Params(
            image_width=16384,
            image_height=16384,
            no_vis=False,
            viewport=(0, 0, 1024, 1024),
        )
        # The viewport pools into frame_max, not the board.
        assert p.frame_factors() == (2, 2)
