"""Temporally-blocked Pallas packed kernel: bit-identity with the XLA packed
engine (itself oracle-gated) in interpret mode.

Real-hardware lowering is exercised by ``bench.py --engine pallas-packed``;
these tests pin the algorithm: halo depth vs generations per launch, wrap
correctness across tile seams, launch splitting (full + remainder).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gol_tpu.models.life import CONWAY, HIGHLIFE
from distributed_gol_tpu.ops import packed, pallas_packed
from tests.conftest import random_board


def run_both(rng, h, w, turns, rule=CONWAY):
    b = random_board(rng, h, w)
    p = packed.pack(jnp.asarray(b))
    got = pallas_packed.make_superstep(rule, interpret=True)(p, turns)
    want = packed.superstep(p, rule, turns)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestTiling:
    def test_headline_shape_deep_blocking(self):
        """16384²: the launch plan must amortise (T deep enough that the
        per-launch overhead term is small) AND keep halo recompute low —
        the cost model's whole point (hw-calibrated, see launch_turns)."""
        t = pallas_packed.launch_turns((16384, 512), 10_000)
        assert t >= 16
        pad = pallas_packed._round8(t)
        tile = pallas_packed._tile_for_pad(16384, 512, pad)
        assert 2 * pad / tile <= 0.05  # redundancy ≤ 5%

    def test_small_board_feasible(self):
        assert pallas_packed.launch_turns((64, 128), 1000) >= 1

    def test_supports(self):
        assert pallas_packed.supports((16384, 512))
        assert not pallas_packed.supports((16384, 64))  # wp % 128 != 0
        assert not pallas_packed.supports((12, 128))  # H % 8 != 0


class TestBitIdentity:
    def test_single_tile_board(self, rng):
        run_both(rng, 64, 4096, turns=20)

    def test_multi_tile_seams(self, rng):
        """H forces several tiles; 40 turns crosses tile boundaries deeply
        enough that any halo under-fill corrupts kept rows."""
        run_both(rng, 256, 4096, turns=40)

    def test_remainder_launch(self, rng):
        """turns chosen so divmod(turns, T) has both full launches and a
        remainder with a different pad."""
        t = pallas_packed.launch_turns((64, 128), 50)
        assert 50 % t != 0 or 50 // t > 1
        run_both(rng, 64, 4096, turns=50)

    def test_zero_turns(self, rng):
        b = random_board(rng, 64, 4096)
        p = packed.pack(jnp.asarray(b))
        got = pallas_packed.make_superstep(CONWAY, interpret=True)(p, 0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(p))

    def test_rule_zoo(self, rng):
        run_both(rng, 64, 4096, turns=12, rule=HIGHLIFE)

    @pytest.mark.parametrize("turns", [1, 7, 8, 9])
    def test_turn_boundaries(self, rng, turns):
        """Around the pad-rounding boundary (multiples of 8)."""
        run_both(rng, 64, 4096, turns=turns)


class TestVerticalPacking:
    @pytest.mark.parametrize("shape", [(32, 128), (64, 256), (96, 128)])
    def test_roundtrip(self, rng, shape):
        b = random_board(rng, *shape)
        got = np.asarray(packed.unpack_vertical(packed.pack_vertical(jnp.asarray(b))))
        np.testing.assert_array_equal(got, b)

    def test_bit_order(self):
        b = np.zeros((64, 128), dtype=np.uint8)
        b[0, 5] = 255  # word row 0, bit 0
        b[33, 7] = 255  # word row 1, bit 1
        p = np.asarray(packed.pack_vertical(jnp.asarray(b)))
        assert p[0, 5] == 1 and p[1, 7] == 2


class TestVmemResident:
    def test_512_board_is_vmem_resident(self):
        assert pallas_packed._vmem_resident_shape(512, 16) == (16, 512)
        assert pallas_packed.is_vmem_resident((512, 16))
        assert pallas_packed.supports((512, 16))

    def test_large_board_is_not(self):
        assert not pallas_packed.is_vmem_resident((16384, 512))

    def test_sublane_alignment_gate(self):
        """H % 256 != 0 puts the sublane count below/off the (8, 128) native
        tile — outside the hardware-validated envelope, so rejected."""
        assert not pallas_packed.is_vmem_resident((128, 4))
        assert not pallas_packed.supports((128, 4))

    @pytest.mark.parametrize("shape,turns", [((512, 512), 30), ((256, 384), 75)])
    def test_bit_identity(self, rng, shape, turns):
        """Whole-superstep-in-one-launch path vs the XLA packed engine,
        including wrap exactness over many generations."""
        assert pallas_packed.is_vmem_resident((shape[0], shape[1] // 32))
        run_both(rng, *shape, turns=turns)

    def test_rule_zoo(self, rng):
        run_both(rng, 256, 128, turns=16, rule=HIGHLIFE)

    def test_bytes_driver(self, rng):
        """make_superstep_bytes dispatches straight to the vertical layout."""
        from tests.oracle import oracle_run as orun

        b = random_board(rng, 256, 128)
        got = pallas_packed.make_superstep_bytes(CONWAY, interpret=True)(
            jnp.asarray(b), 9
        )
        np.testing.assert_array_equal(np.asarray(got), orun(b, 9))


def test_degenerate_width_rejected():
    """Boards narrower than one packed word (wp == 0) are the byte
    engines' business; supports() must not claim them (wp=0 satisfies
    wp % 128 == 0 and once crashed the capability probe at 16x16)."""
    assert not pallas_packed.supports((16, 0))
    assert not pallas_packed.supports((256, 0))
    from distributed_gol_tpu.parallel import pallas_halo

    assert not pallas_halo.supports((16, 0), (1, 1))
