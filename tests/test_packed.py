"""Bit-packed SWAR engine tests: bit-identity with the roll stencil + oracle.

Engines are interchangeable only because each one is gated here against the
same spec (reference kernel ``server/server.go:33-75``); the packed engine
additionally round-trips its uint32 representation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gol_tpu.models.life import CONWAY, RULES
from distributed_gol_tpu.ops import packed
from tests.conftest import random_board
from tests.oracle import oracle_run, oracle_step


def pstep(board, rule=CONWAY):
    return np.asarray(packed.unpack(packed.step(packed.pack(jnp.asarray(board)), rule)))


class TestPacking:
    @pytest.mark.parametrize("shape", [(1, 32), (8, 32), (16, 64), (33, 96), (7, 256)])
    def test_roundtrip(self, rng, shape):
        b = random_board(rng, *shape)
        got = np.asarray(packed.unpack(packed.pack(jnp.asarray(b))))
        np.testing.assert_array_equal(got, b)

    def test_bit_order_lsb_first(self):
        """Bit k of word wx is the cell at column 32*wx + k."""
        b = np.zeros((1, 64), dtype=np.uint8)
        b[0, 0] = 255  # word 0, bit 0
        b[0, 33] = 255  # word 1, bit 1
        p = np.asarray(packed.pack(jnp.asarray(b)))
        assert p[0, 0] == 1 and p[0, 1] == 2

    def test_width_not_multiple_raises(self):
        with pytest.raises(ValueError):
            packed.pack(jnp.zeros((4, 48), dtype=jnp.uint8))

    def test_supports(self):
        assert packed.supports((16, 64))
        assert not packed.supports((64, 16))


class TestStep:
    def test_blinker(self):
        b = np.zeros((5, 32), dtype=np.uint8)
        b[2, 1:4] = 255
        np.testing.assert_array_equal(pstep(b), oracle_step(b))

    @pytest.mark.parametrize(
        "shape", [(1, 32), (2, 32), (3, 64), (16, 32), (64, 64), (33, 96), (128, 128)]
    )
    def test_random_boards_match_oracle(self, rng, shape):
        """Includes the H in {1, 2} degenerate tori and single-word width
        (in-word rotate wrap)."""
        b = random_board(rng, *shape)
        np.testing.assert_array_equal(pstep(b), oracle_step(b))

    @pytest.mark.parametrize("rule", list(RULES.values()), ids=lambda r: r.name)
    def test_rule_zoo(self, rng, rule):
        b = random_board(rng, 32, 64)
        np.testing.assert_array_equal(pstep(b, rule), oracle_step(b, rule))

    def test_edge_wrap_blinkers(self):
        """Blinkers straddling the word boundary and the torus seam — the
        cross-word carry paths of _west/_east."""
        b = np.zeros((8, 64), dtype=np.uint8)
        b[3, 31] = b[3, 32] = b[3, 33] = 255  # across the word 0/1 boundary
        b[6, 63] = b[6, 0] = b[6, 1] = 255  # across the torus seam
        np.testing.assert_array_equal(pstep(b), oracle_step(b))


class TestDrivers:
    def test_superstep_matches_oracle(self, rng):
        b = random_board(rng, 48, 64)
        got = np.asarray(packed.unpack(packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, 12)))
        np.testing.assert_array_equal(got, oracle_run(b, 12))

    def test_steps_with_counts(self, rng):
        b = random_board(rng, 32, 32)
        final, counts = packed.steps_with_counts(packed.pack(jnp.asarray(b)), CONWAY, 8)
        expect = b
        for i in range(8):
            expect = oracle_step(expect)
            assert int(counts[i]) == int((expect == 255).sum()), f"turn {i + 1}"
        np.testing.assert_array_equal(np.asarray(packed.unpack(final)), expect)

    def test_alive_count(self, rng):
        b = random_board(rng, 33, 64)
        assert int(packed.alive_count(packed.pack(jnp.asarray(b)))) == int((b == 255).sum())

    def test_byte_driver_matches_roll_engine(self, rng):
        """The engine-layer drop-ins: uint8 in/out, bit-identical to the roll
        stencil over a long run."""
        from distributed_gol_tpu.ops.stencil import steps_with_counts as roll_counts

        b = random_board(rng, 64, 64)
        run = packed.make_steps_with_counts(CONWAY)
        got_final, got_counts = run(jnp.asarray(b), 32)
        ref_final, ref_counts = roll_counts(
            jnp.asarray(b), jnp.asarray(CONWAY.table), 32
        )
        np.testing.assert_array_equal(np.asarray(got_final), np.asarray(ref_final))
        np.testing.assert_array_equal(np.asarray(got_counts), np.asarray(ref_counts))

    def test_byte_superstep(self, rng):
        b = random_board(rng, 32, 64)
        run = packed.make_superstep(CONWAY)
        np.testing.assert_array_equal(np.asarray(run(jnp.asarray(b), 5)), oracle_run(b, 5))


class TestEngineResolution:
    """Backend.engine_used after capability + superstep fallbacks."""

    def _params(self, **kw):
        from distributed_gol_tpu.engine.params import Params

        return Params(**{"turns": 1000, "image_width": 64, "image_height": 64, **kw})

    def _resolve(self, **kw):
        from distributed_gol_tpu.engine.backend import Backend

        return Backend(self._params(**kw)).engine_used

    def test_auto_prefers_packed_headless(self):
        assert self._resolve(engine="auto") == "packed"

    def test_auto_avoids_packed_per_turn(self):
        """Viewer-attached (superstep 1) runs pay pack/unpack per generation;
        auto must pick roll there."""
        assert self._resolve(engine="auto", no_vis=False) == "roll"
        assert self._resolve(engine="auto", superstep=1) == "roll"

    def test_auto_avoids_packed_for_flip_runs(self):
        """flip_events='cell'/'batch' force superstep 1 in the controller
        even headless; auto must see that through runtime_superstep."""
        assert self._resolve(engine="auto", flip_events="cell") == "roll"
        assert self._resolve(engine="auto", flip_events="batch") == "roll"

    def test_explicit_packed_honoured_per_turn(self):
        assert self._resolve(engine="packed", no_vis=False) == "packed"

    def test_explicit_pallas_packed_on_cpu_interpret(self):
        """Explicit 'pallas-packed' is honoured on CPU via interpret mode
        when the kernel can tile the shape (wp % 128)."""
        got = self._resolve(engine="pallas-packed", image_width=4096, image_height=64)
        assert got == "pallas-packed"
        # untileable width degrades to packed, not roll — and an EXPLICIT
        # engine downgrade warns (the hermetic suite is otherwise
        # warning-clean: pytest.ini escalates uncaptured ones to errors).
        with pytest.warns(RuntimeWarning, match="falling back to 'packed'"):
            assert self._resolve(engine="pallas-packed") == "packed"

    def test_pallas_packed_mesh_degrades_to_packed_halo(self):
        # Round 7: word-aligned (2, 2) tiles RUN the 2-D tile tier now —
        # the degrade survives only where the kernel family can't host
        # the tile (here: 4-row strips, below the 8-row tiling floor),
        # and an explicit request still warns on the way down.
        assert (
            self._resolve(engine="pallas-packed", mesh_shape=(2, 2))
            == "pallas-packed"
        )
        with pytest.warns(RuntimeWarning, match="falling back to 'packed'"):
            assert (
                self._resolve(
                    engine="pallas-packed", mesh_shape=(2, 2),
                    image_width=64, image_height=8,
                )
                == "packed"
            )

    def test_packed_unsupported_width_falls_back(self):
        with pytest.warns(RuntimeWarning, match="falling back to 'roll'"):
            assert (
                self._resolve(engine="packed", image_width=16, image_height=16)
                == "roll"
            )

    def test_sharded_auto_packed(self):
        assert self._resolve(engine="auto", mesh_shape=(2, 2)) == "packed"
        # 64 / 4 = 16 columns per device — not a whole word: roll halo
        # path, chosen by POLICY (round-6 satellite: strips too narrow to
        # hold one packed word are a documented capability bound, not a
        # downgrade — uncaptured engine warnings are errors here, so this
        # resolving silently IS the assertion).
        assert self._resolve(engine="auto", mesh_shape=(2, 4)) == "roll"


@pytest.mark.parametrize("size", [64])
def test_golden_board(golden_images, input_images, size):
    """Direct golden-oracle check: 64²×100 turns vs check/images (the same
    oracle TestGol uses, gol_test.go:24-28)."""
    from distributed_gol_tpu.engine import pgm

    board = pgm.read_pgm(input_images / f"{size}x{size}.pgm")
    run = packed.make_superstep(CONWAY)
    got = np.asarray(run(jnp.asarray(board), 100))
    expect = pgm.read_pgm(golden_images / f"{size}x{size}x100.pgm")
    np.testing.assert_array_equal(got, expect)
