"""Unit tests for the self-healing runtime (ISSUE 5): the GracefulStop
latch (including a real SIGTERM through the installed handler), the
supervisor's restart budget and escalation ladder, the SDC probe's
detection math, and the `corrupt` fault kind's determinism.  The
end-to-end detect→rollback→bit-identical-recovery proofs live in the
chaos matrix (tests/test_chaos.py); these pin the pieces."""

import os
import queue
import signal
import time

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.engine.supervisor import (
    GracefulStop,
    Supervisor,
    supervise,
)
from distributed_gol_tpu.testing.faults import Fault, FaultInjectionBackend, FaultPlan


def small_params(**kw):
    cfg = dict(
        turns=24,
        image_width=16,
        image_height=16,
        engine="roll",
        superstep=4,
        soup_density=0.25,
        soup_seed=11,
        cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return gol.Params(**cfg)


# -- GracefulStop --------------------------------------------------------------

def test_graceful_stop_latch_and_request():
    stop = GracefulStop()
    assert not stop.requested
    stop.request()
    assert stop.requested and stop.signum is None


def test_graceful_stop_install_routes_a_real_sigterm():
    """install() must route an actual delivered signal to the latch and
    hand back a restore that reinstates the previous handler."""
    prev = signal.getsignal(signal.SIGTERM)
    stop = GracefulStop()
    restore = stop.install((signal.SIGTERM,))
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not stop.requested and time.monotonic() < deadline:
            time.sleep(0.01)  # delivery happens between bytecodes
        assert stop.requested
        assert stop.signum == signal.SIGTERM
    finally:
        restore()
    assert signal.getsignal(signal.SIGTERM) is prev


# -- Params validation ---------------------------------------------------------

@pytest.mark.parametrize(
    "kw",
    [
        dict(restart_limit=-1),
        dict(restart_window_seconds=-0.1),
        dict(sdc_check_every_turns=-1),
        # Sentinel coarser than the checkpoint cadence: corruption could
        # be checkpointed before it is ever checked — refused.
        dict(sdc_check_every_turns=8, checkpoint_every_turns=4),
    ],
)
def test_resilience_params_validated(kw):
    with pytest.raises(ValueError):
        small_params(**kw)


# -- restart budget ------------------------------------------------------------

def _bare_supervisor(**kw) -> Supervisor:
    return Supervisor(small_params(restart_limit=2, **kw), queue.Queue())


def test_budget_total_mode():
    sup = _bare_supervisor()
    now = time.monotonic()
    assert sup._budget_allows(now)
    sup.history = [{}, {}]  # two restarts spent
    assert not sup._budget_allows(now)


def test_budget_rate_window_mode():
    """With a window, the limit bounds restarts per trailing window —
    old restarts age out, so a steady trickle keeps being survived."""
    sup = _bare_supervisor(restart_window_seconds=10.0)
    now = time.monotonic()
    sup.history = [{}, {}, {}]  # total is NOT the bound in window mode
    sup._restart_times = [now - 60.0, now - 30.0, now - 2.0]  # one recent
    assert sup._budget_allows(now)
    sup._restart_times = [now - 8.0, now - 2.0]  # two inside the window
    assert not sup._budget_allows(now)


# -- escalation ladder ---------------------------------------------------------

def test_ladder_escalates_to_forced_ppermute():
    """The default rebuild: restart 1 keeps the tier, restart 2 forces
    the ppermute exchange fallback — recorded by the tier policy string
    on a sharded adaptive config."""
    params = small_params(
        engine="pallas-packed",
        mesh_shape=(2, 1),
        skip_stable=True,
        image_width=128,
        image_height=64,
        superstep=6,
        turns=36,
        restart_limit=3,
    )
    sup = Supervisor(params, queue.Queue())
    assert sup._ladder_tier(1) == "same"
    assert sup._ladder_tier(2) == "forced-ppermute"
    b2 = sup._build_backend(2)
    assert b2.sharded_tier == "ppermute"
    assert b2.sharded_tier_policy == "forced-ppermute (in_kernel=False)"


def test_first_attempt_uses_given_backend():
    params = small_params(restart_limit=1)
    backend = Backend(params)
    sup = Supervisor(params, queue.Queue(), backend=backend)
    assert sup._build_backend(0) is backend
    assert sup._build_backend(1) is not backend


# -- SDC probe -----------------------------------------------------------------

def test_sdc_probe_passes_on_clean_dispatch(rng):
    params = small_params()
    backend = Backend(params)
    board = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    out, count = backend.run_turns(board, 4)
    for y0 in (0, 5, 15):  # any stripe start, wraparound included
        ok, pop, fp = backend.sdc_probe(board, out, 4, y0)
        assert ok and pop == count


def test_sdc_probe_catches_bit_flips(rng):
    """Any single toggled cell must be caught: the 16-row board fits one
    stripe, so the redundant roll-stencil recompute sees every cell (and
    the popcount cross-check is parity-protected for odd flip counts)."""
    params = small_params()
    backend = Backend(params)
    board = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    out, count = backend.run_turns(board, 4)
    import jax

    for y, x in ((0, 0), (7, 3), (15, 15)):
        world = np.asarray(jax.device_get(out)).copy()
        world[y, x] ^= 255
        ok, pop, fp = backend.sdc_probe(board, backend.put(world), 4, 5)
        assert not ok or pop != count, f"flip at {(y, x)} went undetected"


def test_sdc_probe_stripe_is_exact_on_tall_boards(rng):
    """A board taller than stripe+2·halo exercises the windowed (partial)
    recompute: it must still pass on clean data for stripes that wrap the
    torus edge."""
    params = small_params(image_width=32, image_height=256, turns=12)
    backend = Backend(params)
    board = backend.put(
        np.where(rng.random((256, 32)) < 0.3, 255, 0).astype(np.uint8)
    )
    out, count = backend.run_turns(board, 4)
    for y0 in (0, 130, 250):
        ok, pop, fp = backend.sdc_probe(board, out, 4, y0)
        assert ok and pop == count


def test_sdc_probe_not_vacuous_on_deep_dispatches(rng):
    """A dispatch deeper than the board (k >= H) collapses the recompute
    window to the whole torus; the comparison must become a FULL-board
    compare, never an empty (vacuously true) slice — clean still passes,
    a popcount-preserving two-cell swap is still caught."""
    params = small_params()
    backend = Backend(params)
    board = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    k = 20  # > H = 16: pad alone exceeds the board
    out, count = backend.run_turns(board, k)
    ok, pop, fp = backend.sdc_probe(board, out, k, 5)
    assert ok and pop == count
    import jax

    world = np.asarray(jax.device_get(out)).copy()
    alive = np.argwhere(world != 0)
    dead = np.argwhere(world == 0)
    world[tuple(alive[0])] ^= 255
    world[tuple(dead[0])] ^= 255  # popcount unchanged: only the stripe can see it
    ok2, pop2, _ = backend.sdc_probe(board, backend.put(world), k, 5)
    assert pop2 == count  # the swap really is popcount-neutral...
    assert not ok2, "popcount-neutral corruption went undetected"


def test_sdc_probe_fingerprint_only_mode(rng):
    """``stripe=False`` (the deep-dispatch escape hatch): the stripe
    recompute is skipped — ``stripe_ok`` is vacuously True even for
    corruption only the stripe could see — while the popcount and
    fingerprint legs still run and match the full probe's."""
    params = small_params()
    backend = Backend(params)
    assert backend.sdc_stripe_affordable(backend._SDC_MAX_STRIPE_TURNS)
    assert not backend.sdc_stripe_affordable(backend._SDC_MAX_STRIPE_TURNS + 1)
    board = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    out, count = backend.run_turns(board, 4)
    ok_full, pop_full, fp_full = backend.sdc_probe(board, out, 4, 5)
    ok, pop, fp = backend.sdc_probe(board, out, 4, 5, stripe=False)
    assert (ok, pop, fp) == (True, pop_full, fp_full)
    import jax

    world = np.asarray(jax.device_get(out)).copy()
    alive = np.argwhere(world != 0)
    dead = np.argwhere(world == 0)
    world[tuple(alive[0])] ^= 255
    world[tuple(dead[0])] ^= 255  # popcount-neutral: invisible to this mode
    corrupted = backend.put(world)
    assert backend.sdc_probe(board, corrupted, 4, 5, stripe=False)[0]
    # ...but an odd flip still trips the popcount leg.
    world[tuple(dead[1])] ^= 255
    _, pop3, _ = backend.sdc_probe(board, backend.put(world), 4, 5, stripe=False)
    assert pop3 != count


def test_deep_dispatch_check_skips_stripe_leg(tmp_path):
    """A dispatch deeper than ``_SDC_MAX_STRIPE_TURNS`` must not replay
    the whole run through the slow formulation: the sentinel drops to
    the popcount/fingerprint leg, counts the skip, and the run completes
    (cap lowered below the superstep to keep the test fast)."""
    params = small_params(sdc_check_every_turns=4, out_dir=tmp_path)
    backend = Backend(params)
    backend._SDC_MAX_STRIPE_TURNS = params.superstep - 1
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=Session(), backend=backend)
    stream = []
    while (e := events.get(timeout=30)) is not None:
        stream.append(e)
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["sdc.checks"] > 0
    assert counters["sdc.stripe_skipped"] == counters["sdc.checks"]
    assert "sdc.mismatches" not in counters


def test_preempt_never_parks_an_unverified_corrupt_board(rng, tmp_path):
    """Verify-before-park covers the EMERGENCY checkpoint too: with the
    sentinel armed, a preemption whose board disagrees with its
    dispatch's forced count (the corrupt-fault signature) raises
    CorruptionDetected BEFORE the save — the corrupt board is never
    durably parked; a truthful count parks normally."""
    from distributed_gol_tpu.engine.controller import (
        Controller,
        CorruptionDetected,
    )

    params = small_params(sdc_check_every_turns=4, out_dir=tmp_path)
    backend = Backend(params)
    board0 = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    board, count = backend.run_turns(board0, 4)
    session = Session(tmp_path / "ckpt")
    ctl = Controller(
        params, queue.Queue(), None, session, backend, stop=GracefulStop()
    )
    ctl._last_resolved = (board, count + 1)  # count no longer matches
    with pytest.raises(CorruptionDetected):
        ctl._preempt_exit(board, 8)
    assert session.check_states(params.image_width, params.image_height) is None

    ctl2 = Controller(
        params, queue.Queue(), None, session, backend, stop=GracefulStop()
    )
    ctl2._last_resolved = (board, count)
    ctl2._preempt_exit(board, 8)
    ckpt = session.check_states(params.image_width, params.image_height)
    assert ckpt is not None and ckpt.turn == 8


class _FailingProbe:
    """Backend proxy whose ``sdc_probe`` raises until told to recover —
    the correlated-failure case: a sick device that corrupts state AND
    fails its own health check."""

    def __init__(self, inner):
        self._inner = inner
        self.healthy = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def sdc_probe(self, *a, **kw):
        if not self.healthy:
            raise RuntimeError("transient probe failure")
        return self._inner.sdc_probe(*a, **kw)


def test_parking_boundary_with_failed_probe_withholds_the_park(rng, tmp_path):
    """Verify-before-park is only as good as the verify: a parking
    boundary whose FORCED check was skipped (transient probe error) must
    not park the never-verified board — the cadence anchor stays put, so
    the next boundary retries and parks once a probe passes."""
    import warnings as warnings_mod

    from distributed_gol_tpu.engine.controller import Controller

    params = small_params(
        sdc_check_every_turns=4, checkpoint_every_turns=4, out_dir=tmp_path
    )
    backend = Backend(params)
    flaky = _FailingProbe(backend)
    board0 = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    board, count = backend.run_turns(board0, 4)
    session = Session(tmp_path / "ckpt")
    ctl = Controller(params, queue.Queue(), None, session, flaky)
    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter("ignore", RuntimeWarning)
        stalled = ctl._guard_boundary(board0, board, 4, 4, count)
    assert stalled  # the probe attempt still hit the device
    assert session.check_states(params.image_width, params.image_height) is None
    assert ctl._last_ckpt_turn == 0  # anchor untouched: next boundary is due
    kinds = [r["kind"] for r in ctl.flight.records()]
    assert "ckpt_skipped_unverified" in kinds

    flaky.healthy = True  # probe recovers: the retried boundary parks
    board2, count2 = backend.run_turns(board, 4)
    ctl._guard_boundary(board, board2, 8, 4, count2)
    ckpt = session.check_states(params.image_width, params.image_height)
    assert ckpt is not None and ckpt.turn == 8


def test_preempt_with_failed_probe_withholds_the_emergency_save(rng, tmp_path):
    """Same policy at the preemption boundary: a skipped forced check
    means the emergency save is withheld — the exit stays resumable from
    the last GOOD checkpoint instead of durably committing an unverified
    board."""
    import warnings as warnings_mod

    from distributed_gol_tpu.engine.controller import Controller

    params = small_params(sdc_check_every_turns=4, out_dir=tmp_path)
    backend = Backend(params)
    flaky = _FailingProbe(backend)
    board0 = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    board, count = backend.run_turns(board0, 4)
    session = Session(tmp_path / "ckpt")
    ctl = Controller(
        params, queue.Queue(), None, session, flaky, stop=GracefulStop()
    )
    ctl._last_resolved = (board, count)
    with warnings_mod.catch_warnings():
        warnings_mod.simplefilter("ignore", RuntimeWarning)
        ctl._preempt_exit(board, 8)
    assert ctl._outcome == "preempted"
    assert session.check_states(params.image_width, params.image_height) is None
    kinds = [r["kind"] for r in ctl.flight.records()]
    assert "preempt_save_skipped" in kinds


def test_sdc_fingerprint_is_deterministic(rng):
    params = small_params()
    backend = Backend(params)
    board = backend.put(
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    )
    out, _ = backend.run_turns(board, 4)
    fp1 = backend.sdc_probe(board, out, 4, 3)[2]
    fp2 = backend.sdc_probe(board, out, 4, 9)[2]  # y0 moves the stripe only
    assert fp1 == fp2  # the fingerprint hashes board_out, not the stripe


# -- the corrupt fault kind ----------------------------------------------------

def test_corrupt_fault_is_deterministic_and_silent(rng):
    """Same plan, same cells: two corrupted runs produce byte-identical
    boards, differing from the clean board in exactly `cells` cells — and
    no exception is raised at the seam."""
    params = small_params()
    plan = FaultPlan([Fault(1, "corrupt", cells=3)])
    boards = []
    for _ in range(2):
        harness = FaultInjectionBackend(Backend(params), plan)
        board = harness.put(
            np.where(
                np.random.default_rng(42).random((16, 16)) < 0.3, 255, 0
            ).astype(np.uint8)
        )
        board, _ = harness.run_turns(board, 4)  # dispatch 0: clean
        board, _ = harness.run_turns(board, 4)  # dispatch 1: corrupted
        boards.append(np.asarray(harness.fetch(board)))
        assert [f.kind for f in harness.injected] == ["corrupt"]
    assert np.array_equal(boards[0], boards[1])

    clean = FaultInjectionBackend(Backend(params), FaultPlan())
    board = clean.put(
        np.where(np.random.default_rng(42).random((16, 16)) < 0.3, 255, 0).astype(
            np.uint8
        )
    )
    board, _ = clean.run_turns(board, 4)
    board, _ = clean.run_turns(board, 4)
    diff = boards[0] != np.asarray(clean.fetch(board))
    assert int(diff.sum()) == 3


def test_corrupt_fault_json_schedulable(tmp_path):
    plan = FaultPlan.from_json('{"faults": [{"at": 2, "kind": "corrupt", "cells": 5}]}')
    assert plan.faults == (Fault(2, "corrupt", cells=5),)
    with pytest.raises(ValueError):
        Fault(0, "corrupt", cells=0)


# -- supervise() plumbing ------------------------------------------------------

def test_sentinel_abort_unsupervised_is_terminal_but_clean(tmp_path):
    """With the supervisor OFF (restart_limit=0, the default), a sentinel
    mismatch keeps PR 2's contract: CorruptionDetected raises, the stream
    ends with the sentinel, the flight record explains the abort — and
    the corrupt board is NOT parked as a resumable checkpoint."""
    params = small_params(sdc_check_every_turns=4, out_dir=tmp_path)
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(1, "corrupt", cells=3)])
    )
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(gol.CorruptionDetected):
        gol.run(params, events, session=session, backend=backend)
    stream = []
    while (e := events.get(timeout=30)) is not None:  # sentinel guaranteed
        stream.append(e)
    errors = [e for e in stream if isinstance(e, gol.DispatchError)]
    assert errors and "SDC sentinel" in errors[-1].error
    assert not errors[-1].checkpointed
    assert session.check_states(16, 16) is None  # corrupt state never parked
    from distributed_gol_tpu.obs import flight as flight_lib

    path = flight_lib.latest_flight_record(tmp_path)
    assert path is not None
    doc = flight_lib.load_flight_record(path)
    assert doc["cause"] == "CorruptionDetected"
    assert "sdc_mismatch" in {r["kind"] for r in doc["records"]}


def test_supervise_returns_supervisor_and_preserves_clean_runs(tmp_path):
    """restart_limit>0 with no faults: the supervised run is byte-for-byte
    a clean run (no restarts, no flight record, stream ends once)."""
    params = small_params(
        restart_limit=2, checkpoint_every_turns=4, out_dir=tmp_path
    )
    events: queue.Queue = queue.Queue()
    sup = supervise(params, events, session=Session())
    stream = []
    while (e := events.get(timeout=30)) is not None:
        stream.append(e)
    assert sup.history == []
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert not list(tmp_path.glob("flight-*.json"))


def test_preempt_at_resume_point_re_parks(tmp_path):
    """Resume is consume-once: a run preempted at its resume point (before
    any new checkpoint) has just CONSUMED the only resumable pair, so the
    emergency checkpoint must re-park the board — skipping on 'already
    saved here' would exit 0 claiming resumable while nothing is."""
    params = small_params(out_dir=tmp_path)
    session = Session()
    world = np.where(
        np.random.default_rng(3).random((16, 16)) < 0.3, 255, 0
    ).astype(np.uint8)
    session.pause(True, world=world, turn=8, rule=params.rule.notation)
    stop = GracefulStop()
    stop.request()  # preemption lands before the first new dispatch
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, stop=stop)
    stream = []
    while (e := events.get(timeout=30)) is not None:
        stream.append(e)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.alive == () and final.completed_turns == 8
    ckpt = session.check_states(16, 16, params.rule.notation)
    assert ckpt is not None and ckpt.turn == 8, "resume point not re-parked"
    assert np.array_equal(ckpt.world, world)


def test_sdc_probe_error_degrades_to_skipped_check(tmp_path):
    """A transient SDC-probe error must not kill the healthy run it was
    checking: the check is skipped with a one-time warning and a counter,
    and the run completes normally."""
    import warnings as warnings_mod

    params = small_params(sdc_check_every_turns=4, out_dir=tmp_path)
    backend = Backend(params)

    class FlakyProbe:
        def __init__(self, inner):
            self._inner = inner
            self.probe_calls = 0

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def sdc_probe(self, *a, **kw):
            self.probe_calls += 1
            if self.probe_calls <= 2:
                raise RuntimeError("transient probe failure")
            return self._inner.sdc_probe(*a, **kw)

    flaky = FlakyProbe(backend)
    events: queue.Queue = queue.Queue()
    with warnings_mod.catch_warnings(record=True) as caught:
        warnings_mod.simplefilter("always")
        gol.run(params, events, session=Session(), backend=flaky)
    stream = []
    while (e := events.get(timeout=30)) is not None:
        stream.append(e)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns  # run survived its checkup
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["sdc.probe_failures"] == 2
    assert counters["sdc.checks"] > 2  # later checks ran (and passed)
    assert "sdc.mismatches" not in counters
    warned = [w for w in caught if "SDC probe" in str(w.message)]
    assert len(warned) == 1  # one warning per run, not per failure
    assert not list(tmp_path.glob("flight-*.json"))


def test_multihost_refuses_restart_limit():
    """The supervisor is single-host for now: run_distributed must refuse
    restart_limit > 0 loudly (validation precedes any collective, so this
    needs no distributed runtime) — silently running WITHOUT the recovery
    the flag promised would be worse than an error."""
    from distributed_gol_tpu.parallel import multihost

    events: queue.Queue = queue.Queue()
    with pytest.raises(ValueError, match="restart_limit"):
        multihost.run_distributed(small_params(restart_limit=1), events)
    assert events.get(timeout=5) is None  # pre-start failures still sentinel


def test_gol_run_routes_to_supervisor(tmp_path):
    """gol.run(params) with restart_limit>0 must survive a terminal burst
    through the DEFAULT rebuild ladder (no factory injection)."""
    params = small_params(
        restart_limit=2, checkpoint_every_turns=4, out_dir=tmp_path
    )
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(2, "issue"), Fault(3, "issue")])
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=Session(), backend=backend)
    stream = []
    while (e := events.get(timeout=30)) is not None:
        stream.append(e)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    assert report.snapshot["counters"]["supervisor.restarts"] == 1
