"""Test session setup: hermetic multi-device JAX on CPU.

The reference's tests require a live AWS broker + 4 workers (SURVEY.md §4);
ours run anywhere by forcing the JAX host platform with 8 virtual devices,
so sharded-mesh tests exercise real collectives (`ppermute`, `psum`) without
TPU hardware.  Must run before the first `import jax` anywhere in the test
process — hence module top-level in conftest.
"""

import os
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A TPU-terminal site hook may have force-selected its own platform via
# jax.config (overriding the env var we just set); re-assert CPU before any
# backend initializes so tests are hermetic on any machine.
jax.config.update("jax_platforms", "cpu")

import faulthandler  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Hang guard (ISSUE 2 satellite): a wedged collective, a watchdog
# regression, or any other hung test must fail tier-1 WITH A TRACEBACK
# instead of silently eating the whole suite budget (the driver's outer
# `timeout 870` kills pytest without a word about which test hung).  Every
# test re-arms a faulthandler dump that prints all thread stacks and
# hard-exits the process if the test is still running after this many
# seconds — generous: the slowest legitimate tier-1 tests (the
# multi-process multihost proofs) bound themselves at 240 s.
_HANG_DUMP_SECONDS = float(os.environ.get("GOL_TEST_HANG_DUMP", "400"))


@pytest.fixture(autouse=True)
def _hang_dump_guard(request):
    # slow-marked suites (excluded from tier-1) legitimately run for
    # many minutes on this 1-core rig — the budget guard is tier-1's,
    # so don't arm it for them.
    armed = _HANG_DUMP_SECONDS > 0 and not request.node.get_closest_marker("slow")
    if armed:
        faulthandler.dump_traceback_later(_HANG_DUMP_SECONDS, exit=True)
    yield
    if armed:
        faulthandler.cancel_dump_traceback_later()

# The reference repo supplies the golden oracles (input soups, golden
# boards, golden count CSVs) — implementation-independent data, read
# in place, never copied into this repo.
REFERENCE_DIR = Path(os.environ.get("GOL_REFERENCE_DIR", "/root/reference"))

needs_reference = pytest.mark.skipif(
    not REFERENCE_DIR.is_dir(),
    reason=f"reference oracle data not mounted at {REFERENCE_DIR}",
)


@pytest.fixture(scope="session")
def reference_dir() -> Path:
    if not REFERENCE_DIR.is_dir():
        pytest.skip("reference oracle data not mounted")
    return REFERENCE_DIR


@pytest.fixture(scope="session")
def golden_images(reference_dir) -> Path:
    return reference_dir / "check" / "images"


@pytest.fixture(scope="session")
def golden_alive(reference_dir) -> Path:
    return reference_dir / "check" / "alive"


@pytest.fixture(scope="session")
def input_images(reference_dir) -> Path:
    return reference_dir / "images"


@pytest.fixture()
def rng() -> np.random.Generator:
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _fresh_default_session():
    """A 'q' detach parks a checkpoint on the global default session (the
    one-broker analog); isolate tests from each other's checkpoints."""
    yield
    from distributed_gol_tpu.engine.session import default_session

    default_session().reset()


def random_board(rng: np.random.Generator, h: int, w: int, p: float = 0.3) -> np.ndarray:
    return np.where(rng.random((h, w)) < p, 255, 0).astype(np.uint8)
