"""Pixel-window (pygame) viewer: the SDL-window contract on the event
stream (``sdl/window.go``, ``sdl/loop.go``), under SDL's dummy
videodriver so it runs headless.

Same discipline as ``tests/test_events_contract.py``: the window's pixel
buffer is built ONLY from the event stream (initial + per-turn flips XOR,
or FrameReady frames), and must agree with the engine's own final state.
"""

import os
import queue

import numpy as np
import pytest

os.environ.setdefault("SDL_VIDEODRIVER", "dummy")

pygame = pytest.importorskip("pygame")

import distributed_gol_tpu as gol  # noqa: E402
from distributed_gol_tpu.viewer.window import Window, run_window  # noqa: E402


def make_params(tmp_path, input_images, **kw):
    defaults = dict(
        turns=20,
        image_width=64,
        image_height=64,
        images_dir=input_images,
        out_dir=tmp_path,
        no_vis=False,
        flip_events="cell",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


class TestWindow:
    def test_flip_pixel_xor_and_bounds(self):
        w = Window(16, 8)
        w.flip_pixel(3, 2)
        assert w.count_pixels() == 1
        w.flip_pixel(3, 2)
        assert w.count_pixels() == 0
        # Bounds panic parity (sdl/window.go:80-83).
        with pytest.raises(IndexError):
            w.flip_pixel(16, 0)
        with pytest.raises(IndexError):
            w.flip_pixel(0, 8)
        with pytest.raises(IndexError):
            w.flip_pixel(-1, 0)
        w.clear_pixels()
        assert w.count_pixels() == 0
        w.render_frame()  # presents without error under the dummy driver
        w.destroy()

    def test_poll_keys_maps_spqk_and_quit(self):
        w = Window(8, 8)
        for key in (pygame.K_s, pygame.K_p, pygame.K_q, pygame.K_k,
                    pygame.K_z):  # z: not a binding, must be ignored
            pygame.event.post(pygame.event.Event(pygame.KEYDOWN, key=key))
        pygame.event.post(pygame.event.Event(pygame.QUIT))
        assert w.poll_keys() == ["s", "p", "q", "k", "q"]

    def test_poll_keys_maps_viewport_pan_zoom(self):
        # ISSUE 11: letters/arrows pan, +/- zoom — the same chars the
        # terminal keyboard forwards (ignored by non-viewport runs).
        w = Window(8, 8)
        for key in (pygame.K_a, pygame.K_d, pygame.K_w, pygame.K_x,
                    pygame.K_LEFT, pygame.K_RIGHT, pygame.K_UP,
                    pygame.K_DOWN, pygame.K_EQUALS, pygame.K_MINUS):
            pygame.event.post(pygame.event.Event(pygame.KEYDOWN, key=key))
        assert w.poll_keys() == [
            "a", "d", "w", "x", "a", "d", "w", "x", "+", "-",
        ]
        w.destroy()


def test_window_shadow_matches_final_board(tmp_path, input_images):
    """Flip-fed window: after the run, the lit pixels are exactly the
    final alive cells (the TestSdl shadow-board contract,
    ``sdl_test.go:107-116``, on the pixel buffer)."""
    params = make_params(tmp_path, input_images)
    events: queue.Queue = queue.Queue()
    gol.run(params, events)

    seen = {}

    class SpyWindow(Window):
        def render_frame(self):
            super().render_frame()
            seen["pixels"] = self._pixels.copy()

    win = SpyWindow(params.image_width, params.image_height)
    final = run_window(params, events, max_fps=1e9, window=win)
    assert final is not None and final.completed_turns == params.turns

    shadow = seen["pixels"]
    want = np.zeros_like(shadow)
    for c in final.alive:
        want[c.y, c.x] = 0xFF
    np.testing.assert_array_equal(shadow, want)


def test_window_frame_mode(tmp_path, input_images):
    """FrameReady-fed window (large-board path, forced small here): the
    buffer is the device-pooled frame, not per-cell flips."""
    params = make_params(
        tmp_path,
        input_images,
        flip_events="auto",
        view_mode="frame",
        frame_max=(16, 16),
        turns=3,
    )
    assert params.wants_frames()
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    win = Window(16, 16)
    final = run_window(params, events, max_fps=1e9, window=win)
    assert final is not None and final.completed_turns == 3
    assert win._pixels.shape == (16, 16)


def test_window_forwards_keys_to_engine(tmp_path, input_images):
    """Keys pressed in the window reach the engine: a 'q' posted to the
    OS queue detaches the run (FinalTurnComplete with empty alive)."""
    import threading
    import time

    # cycle_check=0: the 64² board settles near turn 1584, and the cycle
    # fast-forward would legitimately COMPLETE the 10^9-turn run before
    # the delayed keypress below — this test needs a still-running engine.
    params = make_params(tmp_path, input_images, turns=10**9,
                         turn_events="batch", flip_events="off",
                         cycle_check=0)
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    t = gol.start(params, events, keys)
    pygame.display.init()  # ensure an event queue exists before posting

    def press_q_later():
        time.sleep(1.0)  # let some dispatches land first
        pygame.event.post(pygame.event.Event(pygame.KEYDOWN, key=pygame.K_q))

    threading.Thread(target=press_q_later, daemon=True).start()
    final = run_window(params, events, keys, max_fps=1e9)
    t.join(timeout=60)
    assert final is not None and final.alive == ()
    assert final.completed_turns > 0


def test_cli_window_flag(tmp_path, input_images, capsys):
    from distributed_gol_tpu.__main__ import main

    rc = main(
        ["-w", "16", "-h", "16", "-turns", "3", "--window",
         "--images-dir", str(input_images), "--out-dir", str(tmp_path)]
    )
    assert rc == 0
    assert (tmp_path / "16x16x3.pgm").exists()
    assert "Final turn 3" in capsys.readouterr().out
