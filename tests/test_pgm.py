"""PGM codec tests: byte-compatibility with the reference's files and writer
(gol/io.go:42-128)."""

import numpy as np
import pytest

from distributed_gol_tpu.engine.pgm import PgmError, decode_pgm, encode_pgm, read_pgm, write_pgm
from tests.conftest import random_board


class TestRoundTrip:
    def test_encode_decode(self, rng):
        b = random_board(rng, 17, 33)
        np.testing.assert_array_equal(decode_pgm(encode_pgm(b)), b)

    def test_file_round_trip(self, tmp_path, rng):
        b = random_board(rng, 16, 16)
        p = tmp_path / "sub" / "16x16.pgm"
        write_pgm(p, b)  # creates parent dir, like gol/io.go:44 mkdirs out/
        np.testing.assert_array_equal(read_pgm(p), b)

    def test_header_bytes_match_reference_writer(self):
        """Header must be exactly 'P5\\n{w} {h}\\n255\\n' (gol/io.go:53-60)."""
        b = np.zeros((4, 7), dtype=np.uint8)
        assert encode_pgm(b).startswith(b"P5\n7 4\n255\n")
        assert len(encode_pgm(b)) == len(b"P5\n7 4\n255\n") + 28

    def test_comment_and_whitespace_tolerant(self):
        raw = b"P5 # magic\n# a comment line\n  2\t2\n255\n\x00\xff\xff\x00"
        np.testing.assert_array_equal(
            decode_pgm(raw), np.array([[0, 255], [255, 0]], dtype=np.uint8)
        )


class TestGoldenFiles:
    def test_reads_reference_input(self, input_images):
        b = read_pgm(input_images / "16x16.pgm")
        assert b.shape == (16, 16)
        assert set(np.unique(b)) <= {0, 255}

    def test_reencode_is_byte_identical(self, input_images):
        """encode(decode(x)) == x for every reference input soup: proof the
        writer is byte-compatible with the reference corpus."""
        for p in sorted(input_images.glob("*.pgm")):
            raw = p.read_bytes()
            assert encode_pgm(decode_pgm(raw)) == raw, p.name


class TestErrors:
    def test_bad_magic(self):
        with pytest.raises(PgmError):
            decode_pgm(b"P2\n2 2\n255\n1 2 3 4")

    def test_bad_maxval(self):
        with pytest.raises(PgmError):
            decode_pgm(b"P5\n1 1\n65535\n\x00\x00")

    def test_truncated(self):
        with pytest.raises(PgmError):
            decode_pgm(b"P5\n4 4\n255\n\x00")
