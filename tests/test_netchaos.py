"""Wire chaos: deterministic fault injection against every wire hop.

ISSUE 20.  The tentpole test (``TestChaosMatrix``) runs a broker, two
pods, and a depth-2 relay chain with EVERY hop behind a seeded
``ChaosProxy`` — latency, trickle, disconnect, corrupt, stall — and
asserts the cluster converges to a bit-identical final board versus a
fault-free oracle, answers ``/healthz`` in bounded time throughout,
and leaks neither threads nor sockets.  Around it: unit tests for the
proxy itself, WS keepalive + malformed-frame fuzz, httpd hardening
(408/413/503), gateway idempotency, client deadlines, half-open stall
detection pins for the relay and the broker probe loop, and the
socket-hygiene lint gate.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from distributed_gol_tpu.engine import frames as frames_lib
from distributed_gol_tpu.engine.events import FrameReady
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.serve import (
    GatewayServer,
    RelayServer,
    ServeConfig,
    ServePlane,
)
from distributed_gol_tpu.serve import wire
from distributed_gol_tpu.serve import ws as ws_lib
from distributed_gol_tpu.serve.broker import Broker, BrokerConfig
from distributed_gol_tpu.serve.httpd import StdlibHTTPServer, read_body
from distributed_gol_tpu.serve.podclient import (
    IDEMPOTENCY_HEADER,
    PodClient,
    PodHTTPError,
)
from distributed_gol_tpu.testing.netchaos import (
    WIRE_FAULT_KINDS,
    ChaosProxy,
    WireFault,
    WirePlan,
)
from tools.gol_client import GolClient

REPO = Path(__file__).resolve().parent.parent

W = H = 32

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def spec_doc(turns, seed, spectate=False, checkpoint_every=0):
    doc = {
        "params": {
            "width": W,
            "height": H,
            "turns": turns,
            "engine": "roll",
            "superstep": 4,
            "cycle_check": 0,
            "ticker_period": 60.0,
        },
        "soup": {"seed": seed, "density": 0.3},
    }
    if spectate:
        doc["spectate"] = True
        doc["viewport"] = [0, 0, W, H]
    if checkpoint_every:
        doc["checkpoint_every"] = checkpoint_every
    return doc


def submit_via(client, tenant, spec):
    body = dict(json.loads(json.dumps(spec)))
    body["tenant"] = tenant
    return client._request("POST", "/v1/sessions", body)


def wait_for(predicate, timeout=30.0, what="condition", interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def counter(name):
    snap = obs_metrics.REGISTRY.snapshot().to_dict()
    return snap["counters"].get(name, 0)


def broker_state(client, tenant):
    """State poll that survives a chaotic wire: any transport error or
    corrupted body reads as "not there yet"."""
    try:
        st = client.state(tenant)
    except Exception:
        return None
    if not isinstance(st, dict) or "status" not in st:
        return None
    return st


def chaos_submit(client, tenant, spec, timeout=60.0):
    """Submit through a faulty wire.  A retried POST after an eaten 201
    lands a 409 from the pod — any exception falls back to a state
    poll; success == the session exists."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            return submit_via(client, tenant, spec)
        except Exception as exc:  # noqa: BLE001 - chaos path
            last = exc
            st = broker_state(client, tenant)
            if st is not None:
                return st
            time.sleep(0.2)
    raise AssertionError(
        f"chaos submit for {tenant!r} never landed: {last!r}"
    )


def oracle_final(tmp_path, tenant, spec):
    """Fault-free oracle: the same spec through an undisturbed plane."""
    params, _ = wire.params_from_spec(
        tenant, json.loads(json.dumps(spec)), root=tmp_path / "oracle-up"
    )
    with ServePlane(
        ServeConfig(max_sessions=1),
        checkpoint_root=tmp_path / f"oracle-{tenant}",
    ) as plane:
        handle = plane.submit(tenant, params)
        assert handle.wait(timeout=120)
        assert handle.status == "completed"
        return np.asarray(handle.final)


def chaos_threads():
    return [
        t.name
        for t in threading.enumerate()
        if t.name.startswith("gol-netchaos")
    ]


def want_board(final):
    return (np.asarray(final) != 0).astype(np.uint8) * np.uint8(255)


def event_board(ev, size):
    """A FinalTurnComplete's alive-cell list as a 0/255 board."""
    board = np.zeros((size, size), np.uint8)
    for x, y in ev.alive:
        board[y, x] = 255
    return board


def final_board(client, tenant, size):
    """The final board via the controller replay ring (the oracle a
    frame-stream drain never touches)."""
    with client.controller(tenant) as ctrl:
        while True:
            msg = ctrl.recv(timeout=30)
            if msg["type"] == "final":
                board = np.zeros((size, size), np.uint8)
                for x, y in msg["alive"]:
                    board[y, x] = 255
                return board
            if msg["type"] == "end":
                raise AssertionError("stream ended without a final")


def pause_session(gateway, tenant, timeout=30.0):
    wait_for(
        lambda: tenant in gateway._sessions,
        timeout,
        f"session {tenant!r}",
    )
    s = gateway._sessions[tenant]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s.pause()
        if s.paused:
            return s
        time.sleep(0.002)
    raise AssertionError(f"could not pause {tenant!r}")


class Echo:
    """Tiny TCP echo server — the proxy unit tests' upstream."""

    def __init__(self):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self._srv.settimeout(0.2)
        self.host, self.port = self._srv.getsockname()
        self.accepted = 0
        self._closing = False
        self._threads = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="test-echo-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.accepted += 1
            t = threading.Thread(
                target=self._serve, args=(conn,),
                name="test-echo-conn", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        conn.settimeout(0.2)
        try:
            while not self._closing:
                try:
                    data = conn.recv(4096)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    return
                conn.sendall(data)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(2.0)
        for t in self._threads:
            t.join(2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class StreamDrain:
    """Drains a frame stream into a board, folding keyframes + deltas."""

    def __init__(self, host, port, path, sock_timeout=120.0):
        self.host, self.port, self.path = host, port, path
        self.sock_timeout = sock_timeout
        self.buf = None
        self.turn = -1
        self.frames = 0
        self.ended = False
        self.error = None
        self._ws = None
        self.thread = threading.Thread(
            target=self._run, name="test-stream-drain", daemon=True
        )

    def start(self):
        self.thread.start()
        return self

    def _run(self):
        try:
            ws = ws_lib.client_connect(
                self.host, self.port, self.path, timeout=30.0
            )
            self._ws = ws
            ws._sock.settimeout(self.sock_timeout)
            while True:
                op, payload = ws.recv()
                if op == ws_lib.OP_TEXT:
                    doc = json.loads(payload.decode("utf-8"))
                    if doc.get("type") == "end":
                        self.ended = True
                        return
                    continue
                ev = wire.decode_frame_event(bytes(payload))
                if isinstance(ev, FrameReady):
                    self.buf = np.array(
                        ev.frame, dtype=np.uint8, copy=True
                    )
                elif self.buf is not None:
                    frames_lib.apply_bands(self.buf, ev.bands)
                self.turn = ev.completed_turns
                self.frames += 1
        except Exception as exc:  # noqa: BLE001 - surfaced via .error
            self.error = exc
        finally:
            if self._ws is not None:
                try:
                    self._ws.abort()
                except OSError:
                    pass

    def join(self, timeout=120.0):
        self.thread.join(timeout)
        assert not self.thread.is_alive(), "drain thread stuck"
        if self.error is not None:
            raise self.error


# ---------------------------------------------------------------------------
# WireFault / WirePlan: the deterministic schedule
# ---------------------------------------------------------------------------


class TestWirePlan:
    def test_fault_validation(self):
        with pytest.raises(ValueError):
            WireFault(0, "gremlins")
        with pytest.raises(ValueError):
            WireFault(-1, "latency")
        with pytest.raises(ValueError):
            WireFault(0, "latency", seconds=-0.1)
        with pytest.raises(ValueError):
            WireFault(0, "corrupt", after_bytes=-1)

    def test_duplicate_connection_index_rejected(self):
        with pytest.raises(ValueError):
            WirePlan(
                [WireFault(2, "latency"), WireFault(2, "disconnect")]
            )

    def test_lookup_and_ordering(self):
        plan = WirePlan(
            [WireFault(5, "stall"), WireFault(1, "latency", seconds=0.2)]
        )
        assert [f.at for f in plan.faults] == [1, 5]
        assert plan.fault_at(1).kind == "latency"
        assert plan.fault_at(5).kind == "stall"
        assert plan.fault_at(0) is None
        assert plan.fault_at(3) is None

    def test_random_is_seed_deterministic(self):
        a = WirePlan.random(7, 64, p_fault=0.4)
        b = WirePlan.random(7, 64, p_fault=0.4)
        c = WirePlan.random(8, 64, p_fault=0.4)
        assert a.faults == b.faults
        assert a.faults != c.faults

    def test_random_edges_and_kinds(self):
        assert WirePlan.random(3, 32, p_fault=0.0).faults == ()
        dense = WirePlan.random(3, 32, p_fault=1.0)
        assert len(dense.faults) == 32
        only = WirePlan.random(5, 64, p_fault=1.0, kinds=("corrupt",))
        assert {f.kind for f in only.faults} == {"corrupt"}
        for kind in WIRE_FAULT_KINDS:
            assert isinstance(kind, str)

    def test_from_json_scripted_and_seeded(self, tmp_path):
        scripted = WirePlan.from_json(
            json.dumps(
                {
                    "faults": [
                        {"at": 0, "kind": "latency", "seconds": 0.1},
                        {"at": 2, "kind": "corrupt", "after_bytes": 9},
                    ]
                }
            )
        )
        assert scripted.fault_at(0).seconds == 0.1
        assert scripted.fault_at(2).after_bytes == 9

        p = tmp_path / "plan.json"
        p.write_text(
            json.dumps({"seed": 7, "n_connections": 64, "p_fault": 0.4})
        )
        assert (
            WirePlan.from_json(str(p)).faults
            == WirePlan.random(7, 64, p_fault=0.4).faults
        )
        assert WirePlan.from_json("{}").faults == ()
        with pytest.raises(ValueError):
            WirePlan.from_json(json.dumps([1, 2, 3]))


# ---------------------------------------------------------------------------
# ChaosProxy semantics, one fault kind at a time (against a TCP echo)
# ---------------------------------------------------------------------------


def echo_rtt(proxy, payload=b"ping-pong", timeout=5.0):
    """One connect + echo round trip through the proxy; returns
    (reply, elapsed_seconds)."""
    t0 = time.monotonic()
    with socket.create_connection(
        (proxy.host, proxy.port), timeout=timeout
    ) as s:
        s.settimeout(timeout)
        s.sendall(payload)
        got = b""
        while len(got) < len(payload):
            chunk = s.recv(4096)
            if not chunk:
                break
            got += chunk
    return got, time.monotonic() - t0


class TestChaosProxy:
    def test_clean_passthrough(self):
        with Echo() as echo:
            with ChaosProxy((echo.host, echo.port)) as proxy:
                got, _ = echo_rtt(proxy, b"hello wire")
                assert got == b"hello wire"
                assert proxy.fired == []
                assert proxy.connections == 1
            assert proxy.open_connections() == 0

    def test_latency_delays_but_delivers(self):
        plan = WirePlan([WireFault(0, "latency", seconds=0.3)])
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan
        ) as proxy:
            got, dt = echo_rtt(proxy)
            assert got == b"ping-pong"
            assert 0.3 <= dt < 5.0
            assert [f.kind for f in proxy.fired] == ["latency"]

    def test_trickle_preserves_bytes(self):
        plan = WirePlan([WireFault(0, "trickle", seconds=0.002)])
        payload = bytes(range(64))
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan
        ) as proxy:
            got, _ = echo_rtt(proxy, payload, timeout=10.0)
            assert got == payload
            assert [f.kind for f in proxy.fired] == ["trickle"]

    def test_corrupt_flips_exactly_one_byte(self):
        plan = WirePlan([WireFault(0, "corrupt", after_bytes=5)])
        payload = bytes(range(16))
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan
        ) as proxy:
            got, _ = echo_rtt(proxy, payload)
            assert len(got) == 16
            want = bytearray(payload)
            want[5] ^= 0xFF
            assert got == bytes(want)

    def test_disconnect_cuts_after_offset(self):
        plan = WirePlan([WireFault(0, "disconnect", after_bytes=8)])
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan
        ) as proxy:
            with socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                s.sendall(bytes(range(32)))
                got = b""
                while True:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    got += chunk
            assert len(got) == 8

    def test_disconnect_at_accept(self):
        plan = WirePlan([WireFault(0, "disconnect")])
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan
        ) as proxy:
            with socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                assert s.recv(1) == b""
            assert echo.accepted == 0

    def test_blackhole_never_reaches_upstream(self):
        plan = WirePlan([WireFault(0, "blackhole")])
        with Echo() as echo:
            proxy = ChaosProxy((echo.host, echo.port), plan)
            try:
                with socket.create_connection(
                    (proxy.host, proxy.port), timeout=5.0
                ) as s:
                    s.settimeout(0.4)
                    s.sendall(b"anyone home?")
                    with pytest.raises(socket.timeout):
                        s.recv(1)
                assert echo.accepted == 0
                assert proxy.open_connections() == 1
            finally:
                proxy.close()
            assert proxy.open_connections() == 0

    def test_stall_goes_half_open_and_pins(self):
        plan = WirePlan([WireFault(0, "stall", after_bytes=4)])
        with Echo() as echo:
            proxy = ChaosProxy((echo.host, echo.port), plan)
            try:
                with socket.create_connection(
                    (proxy.host, proxy.port), timeout=5.0
                ) as s:
                    s.settimeout(0.5)
                    s.sendall(bytes(range(16)))
                    got = b""
                    with pytest.raises(socket.timeout):
                        while True:
                            chunk = s.recv(4096)
                            if not chunk:
                                break
                            got += chunk
                    assert len(got) == 4
                    assert proxy.stalled_connections() == 1
            finally:
                proxy.close()
            assert proxy.stalled_connections() == 0
            assert proxy.open_connections() == 0

    def test_stall_self_releases_after_hang_seconds(self):
        plan = WirePlan([WireFault(0, "stall")])
        with Echo() as echo, ChaosProxy(
            (echo.host, echo.port), plan, hang_seconds=0.4
        ) as proxy:
            with socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                s.sendall(b"x")
                t0 = time.monotonic()
                assert s.recv(1) == b""  # hang timer tore the pair down
                assert time.monotonic() - t0 < 5.0
            wait_for(
                lambda: proxy.stalled_connections() == 0,
                5.0,
                "stall self-release",
            )

    def test_url_and_upstream_forms(self):
        with Echo() as echo:
            with ChaosProxy(f"http://{echo.host}:{echo.port}") as proxy:
                assert proxy.url.startswith("http://127.0.0.1:")
                got, _ = echo_rtt(proxy, b"via-url")
                assert got == b"via-url"

    def test_set_plan_relative_rebases_to_next_connection(self):
        with Echo() as echo, ChaosProxy((echo.host, echo.port)) as proxy:
            for _ in range(3):
                echo_rtt(proxy)
            proxy.set_plan(
                WirePlan([WireFault(0, "disconnect")]), relative=True
            )
            with socket.create_connection(
                (proxy.host, proxy.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                assert s.recv(1) == b""
            got, _ = echo_rtt(proxy)  # fault consumed; next conn clean
            assert got == b"ping-pong"


# ---------------------------------------------------------------------------
# WS keepalive + malformed frames, unit level (socketpair, no HTTP)
# ---------------------------------------------------------------------------


def ws_pair(max_payload=1 << 20):
    """(websocket, peer raw socket) over a socketpair — the peer plays
    an arbitrary (possibly hostile) remote."""
    a, b = socket.socketpair()
    ws = ws_lib.WebSocket(
        a.makefile("rb"), a.makefile("wb"), mask=False, sock=a,
        max_payload=max_payload,
    )
    b.settimeout(5.0)
    return ws, a, b


class TestWsKeepaliveUnit:
    def test_silent_peer_times_out_within_budget(self):
        ws, a, b = ws_pair()
        try:
            ws.enable_keepalive(0.15, misses=2)
            t0 = time.monotonic()
            with pytest.raises(ws_lib.WsTimeout):
                ws.recv()
            dt = time.monotonic() - t0
            assert 0.2 <= dt <= 1.5
        finally:
            a.close()
            b.close()

    def test_live_peer_survives_silence_past_budget(self):
        ws, a, b = ws_pair()
        stop = threading.Event()

        peer_ws = ws_lib.WebSocket(
            b.makefile("rb"), b.makefile("wb"), mask=True, sock=b
        )

        def peer():
            """Pongs every ping from t=0 — alive, just no data."""
            try:
                while not stop.is_set():
                    peer_ws.recv()  # auto-pong keeps us honest
            except (ws_lib.WsClosed, OSError):
                pass

        t = threading.Thread(target=peer, daemon=True)
        t.start()
        speak = threading.Timer(
            0.8, lambda: peer_ws.send_text("late but alive")
        )
        speak.start()
        try:
            ws.enable_keepalive(0.15, misses=2)
            op, payload = ws.recv()
            assert op == ws_lib.OP_TEXT
            assert payload == b"late but alive"
        finally:
            stop.set()
            # shutdown (not just close) wakes the peer thread blocked in
            # recv — close() alone leaves it parked until the join cap.
            for s in (a, b):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            a.close()
            b.close()
            t.join(5.0)

    def test_keepalive_toggle_remembers_policy(self):
        ws, a, b = ws_pair()
        try:
            assert ws.keepalive is None
            ws.enable_keepalive(0.25, misses=4)
            assert ws.keepalive == (0.25, 4)
            # Suspending hands the deadline to explicit settimeout
            # polls but REMEMBERS the policy for re-arming.
            ws.disable_keepalive()
            assert ws.keepalive == (0.25, 4)
            ws.enable_keepalive(*ws.keepalive)
            assert ws.keepalive == (0.25, 4)
            with pytest.raises(ValueError):
                ws.enable_keepalive(0.0)
            with pytest.raises(ValueError):
                ws.enable_keepalive(1.0, misses=0)
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize(
        "blob,reason",
        [
            (bytes([0x91, 0x00]), "reserved RSV bits"),
            (bytes([0x09, 0x00]), "fragmented control frame"),
            (bytes([0x89, 0x7E, 0x00, 0x80]), "oversize control frame"),
        ],
    )
    def test_malformed_unit_frames_close_cleanly(self, blob, reason):
        ws, a, b = ws_pair()
        try:
            b.sendall(blob)
            with pytest.raises(ws_lib.WsClosed):
                ws.recv()
        finally:
            a.close()
            b.close()

    def test_oversize_declaration_closes(self):
        ws, a, b = ws_pair(max_payload=256)
        try:
            b.sendall(bytes([0x82, 0x7F]) + struct.pack(">Q", 1 << 30))
            with pytest.raises(ws_lib.WsClosed):
                ws.recv()
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# httpd hardening: 408 slow-loris, 413 oversize, 503 shed
# ---------------------------------------------------------------------------


class PingServer(StdlibHTTPServer):
    """Minimal wire target: GET /ping, POST /echo."""

    def handle(self, request, method, path, query):
        if method == "GET" and path == "/ping":
            request._send_json(200, {"ok": True})
            return True
        if method == "POST" and path == "/echo":
            body = read_body(request)
            request._send_json(200, {"n": len(body)})
            return True
        return False


def raw_get(host, port, path="/ping", timeout=5.0):
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(
            f"GET {path} HTTP/1.1\r\nHost: x\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        data = b""
        while True:
            chunk = s.recv(4096)
            if not chunk:
                break
            data += chunk
    return data


class TestHttpdHardening:
    def test_slowloris_reaped_with_408(self):
        srv = PingServer(port=0, read_timeout=0.3)
        try:
            base = counter("net.slowloris_reaped")
            with socket.create_connection(
                (srv.host, srv.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                s.sendall(b"GET /pi")  # ...and then never finishes
                data = b""
                while True:
                    try:
                        chunk = s.recv(4096)
                    except socket.timeout:
                        break
                    if not chunk:
                        break
                    data += chunk
            assert b"408" in data
            assert counter("net.slowloris_reaped") == base + 1
        finally:
            srv.close()

    def test_oversize_body_rejected_with_413(self):
        srv = PingServer(port=0, body_cap=1024)
        try:
            base = counter("net.oversize_rejected")
            conn = http.client.HTTPConnection(
                srv.host, srv.port, timeout=5.0
            )
            try:
                conn.request(
                    "POST", "/echo", body=b"x" * 4096,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                assert resp.status == 413
                resp.read()
            finally:
                conn.close()
            assert counter("net.oversize_rejected") == base + 1

            conn = http.client.HTTPConnection(
                srv.host, srv.port, timeout=5.0
            )
            try:
                conn.request("POST", "/echo", body=b"y" * 512)
                resp = conn.getresponse()
                assert resp.status == 200
                assert json.loads(resp.read())["n"] == 512
            finally:
                conn.close()
        finally:
            srv.close()

    def test_connection_shed_with_503(self):
        srv = PingServer(port=0, max_connections=1)
        try:
            base = counter("net.connections_shed")
            # Conn 1 parks mid-request on the only slot: with no read
            # deadline configured the handler blocks in readline and
            # the slot stays held for as long as we like.
            hog = socket.create_connection(
                (srv.host, srv.port), timeout=5.0
            )
            try:
                hog.sendall(b"GET /pi")  # never finished
                # The slot is acquired on the accept thread; give it a
                # few attempts to have landed before the shed probe.
                for attempt in range(5):
                    data = raw_get(srv.host, srv.port)
                    if b"503" in data.split(b"\r\n", 1)[0]:
                        break
                    time.sleep(0.05)
                else:
                    raise AssertionError(f"no 503 over 5 sheds: {data!r}")
                assert counter("net.connections_shed") >= base + 1
            finally:
                hog.close()
        finally:
            srv.close()

    def test_hardening_defaults_off(self):
        srv = PingServer(port=0)
        try:
            with socket.create_connection(
                (srv.host, srv.port), timeout=5.0
            ) as s:
                s.settimeout(5.0)
                s.sendall(b"GET /ping HTTP/1.1\r\nHost: x\r\n")
                time.sleep(0.5)  # no read_timeout: slow is tolerated
                s.sendall(b"Connection: close\r\n\r\n")
                data = b""
                while True:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    data += chunk
            assert b"200" in data.split(b"\r\n", 1)[0]
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Gateway idempotency: replayed receipts instead of double placement
# ---------------------------------------------------------------------------


def post_sessions(gw, doc, key=None):
    """Raw POST /v1/sessions with an optional idempotency key; returns
    (status, body-dict, replay-header-or-None)."""
    conn = http.client.HTTPConnection(gw.host, gw.port, timeout=10.0)
    try:
        headers = {"Content-Type": "application/json"}
        if key:
            headers[IDEMPOTENCY_HEADER] = key
        conn.request(
            "POST", "/v1/sessions",
            body=json.dumps(doc).encode(), headers=headers,
        )
        resp = conn.getresponse()
        body = json.loads(resp.read() or b"{}")
        return resp.status, body, resp.getheader("X-Gol-Idempotent-Replay")
    finally:
        conn.close()


class TestGatewayIdempotency:
    def test_same_key_replays_identical_receipt(self, tmp_path):
        plane = ServePlane(
            ServeConfig(max_sessions=4), checkpoint_root=tmp_path / "c"
        )
        gw = GatewayServer(plane, port=0)
        try:
            base = counter("net.idempotent_replays")
            # Long enough that the session is still live for every POST
            # below — a completed session frees the tenant slot and a
            # keyless resubmit would be honestly re-ADMITTED (201).
            doc = {"tenant": "alice", **spec_doc(3000, 3)}
            st1, body1, rp1 = post_sessions(gw, doc, key="k-alice-1")
            assert st1 == 201
            assert rp1 is None
            st2, body2, rp2 = post_sessions(gw, doc, key="k-alice-1")
            assert (st2, body2) == (st1, body1)
            assert rp2 == "1"
            assert counter("net.idempotent_replays") == base + 1
            # One session, not two: a keyless resubmit is a real
            # rejection (409 permanent or 429 shed), never a replay.
            st3, _, rp3 = post_sessions(gw, doc)
            assert st3 in (409, 429)
            assert rp3 is None
        finally:
            gw.close()
            plane.close()

    def test_receipt_ring_evicts_oldest(self, tmp_path):
        plane = ServePlane(
            ServeConfig(max_sessions=4, idempotency_cache_size=2),
            checkpoint_root=tmp_path / "c",
        )
        gw = GatewayServer(plane, port=0)
        try:
            for i, tenant in enumerate(("t0", "t1", "t2")):
                doc = {"tenant": tenant, **spec_doc(8, 3 + i)}
                st, _, _ = post_sessions(gw, doc, key=f"k-{tenant}")
                assert st == 201
            # k-t0 was evicted (ring holds 2): the retry falls through
            # to admission — whatever admission says, it is NOT a
            # replayed receipt.
            st, _, rp = post_sessions(
                gw, {"tenant": "t0", **spec_doc(8, 3)}, key="k-t0"
            )
            assert st in (201, 409, 429)
            assert rp is None
            # k-t2 is still in the ring.
            st, _, rp = post_sessions(
                gw, {"tenant": "t2", **spec_doc(8, 5)}, key="k-t2"
            )
            assert st == 201
            assert rp == "1"
        finally:
            gw.close()
            plane.close()


class FlakyPod(StdlibHTTPServer):
    """Eats the first POST /v1/sessions mid-response, answers the
    retry — records the idempotency key each attempt carried."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.keys = []

    def handle(self, request, method, path, query):
        if method == "POST" and path == "/v1/sessions":
            read_body(request)
            self.keys.append(request.headers.get(IDEMPOTENCY_HEADER))
            if len(self.keys) == 1:
                request.connection.shutdown(socket.SHUT_RDWR)
                raise BrokenPipeError("chaos: ate the response")
            request._send_json(201, {"tenant": "alice"})
            return True
        if method == "GET" and path == "/big":
            request._send_json(200, {"pad": "x" * 4096})
            return True
        return False


class TestPodClientHardening:
    def test_retry_reuses_one_idempotency_key(self):
        pod = FlakyPod(port=0)
        try:
            client = PodClient(
                pod.url, attempts=2, backoff_seconds=0.01,
                backoff_max_seconds=0.05,
            )
            doc = client.submit({"tenant": "alice", **spec_doc(8, 3)})
            assert doc == {"tenant": "alice"}
            assert len(pod.keys) == 2
            assert pod.keys[0] is not None
            assert pod.keys[0] == pod.keys[1]
        finally:
            pod.close()

    def test_response_cap_rejects_oversize_body(self):
        pod = FlakyPod(port=0)
        try:
            client = PodClient(pod.url, response_cap=512)
            with pytest.raises(PodHTTPError) as exc:
                client.request("GET", "/big")
            assert "cap" in str(exc.value)
        finally:
            pod.close()


# ---------------------------------------------------------------------------
# Client deadlines (satellite: tools/gol_client.py hardening)
# ---------------------------------------------------------------------------


class TestClientDeadlines:
    def test_stalled_gateway_fails_fast_not_forever(self, tmp_path):
        plane = ServePlane(
            ServeConfig(max_sessions=1), checkpoint_root=tmp_path / "c"
        )
        gw = GatewayServer(plane, port=0)
        proxy = ChaosProxy(
            (gw.host, gw.port),
            WirePlan([WireFault(0, "stall")]),
            hang_seconds=30.0,
        )
        try:
            client = GolClient(
                proxy.url, timeout=1.0, connect_timeout=1.0
            )
            t0 = time.monotonic()
            with pytest.raises((OSError, TimeoutError)):
                client.state("nobody")
            assert time.monotonic() - t0 < 3.0
        finally:
            proxy.close()
            gw.close()
            plane.close()

    def test_connect_timeout_defaults(self):
        assert GolClient("http://127.0.0.1:9", timeout=3.0).connect_timeout == 3.0
        assert (
            GolClient("http://127.0.0.1:9", timeout=60.0).connect_timeout
            == 10.0
        )


# ---------------------------------------------------------------------------
# WS fuzz: seeded malformed frames against a live gateway (satellite)
# ---------------------------------------------------------------------------


def _fuzz_blobs(rng):
    """One malformed wire blob per call, seeded — every shape the
    issue names: truncated headers, torn payloads, RSV bits,
    fragmented control frames, over-length declarations, garbage."""
    shapes = (
        lambda: bytes([rng.randrange(256)]),                    # truncated header
        lambda: bytes([0x81, 10]) + bytes(3),                   # torn payload
        lambda: bytes(
            [0x80 | rng.choice((0x10, 0x20, 0x40, 0x70)) | 0x1, 0x00]
        ),                                                      # RSV bits
        lambda: bytes([0x09, 0x00]),                            # fragmented ctrl
        lambda: bytes([0x82, 0x7F])
        + struct.pack(">Q", (1 << 40) + rng.randrange(1 << 20)),  # oversize decl
        lambda: bytes([0x89, 0x7E, 0x00, 0xFE]),                # oversize ctrl
        lambda: bytes(
            rng.randrange(256) for _ in range(rng.randrange(8, 160))
        ),                                                      # garbage
    )
    return rng.choice(shapes)()


class TestWsFuzz:
    def test_malformed_frames_never_wedge_the_gateway(self, tmp_path):
        plane = ServePlane(
            ServeConfig(max_sessions=2), checkpoint_root=tmp_path / "c"
        )
        gw = GatewayServer(plane, port=0)
        try:
            client = GolClient(gw.url)
            submit_via(client, "alice", spec_doc(4000, 11, spectate=True))
            pause_session(gw, "alice")

            def reader_threads():
                return sum(
                    1
                    for t in threading.enumerate()
                    if t.name.startswith("gol-gateway-ws-reader")
                )

            rng = random.Random(0x600D5EED)
            path = "/v1/sessions/alice/frames?queue=64"
            # Two full passes over every malformed shape (the blob menu
            # is 7 entries sampled round-robin-ish by the seeded rng).
            for _ in range(14):
                ws = ws_lib.client_connect(
                    gw.host, gw.port, path, timeout=10.0
                )
                try:
                    ws._sock.sendall(_fuzz_blobs(rng))
                    ws._sock.settimeout(0.2)
                    try:
                        while ws._sock.recv(4096):
                            pass
                    except socket.timeout:
                        pass
                finally:
                    ws.abort()

            # The gateway still answers health in bounded time...
            t0 = time.monotonic()
            with urllib.request.urlopen(
                f"{gw.url}/healthz", timeout=2.0
            ) as resp:
                assert resp.status == 200
            assert time.monotonic() - t0 < 2.0

            # ...still serves a clean spectator...
            ws = ws_lib.client_connect(
                gw.host, gw.port, path, timeout=10.0
            )
            try:
                ws._sock.settimeout(10.0)
                op, payload = ws.recv()
                assert op == ws_lib.OP_TEXT
                assert json.loads(payload)["type"] == "hello"
            finally:
                ws.abort()

            # ...and its reader threads drained back to zero.
            wait_for(
                lambda: reader_threads() == 0,
                15.0,
                "gateway ws-reader threads to drain",
            )
        finally:
            gw.close()
            plane.close()


# ---------------------------------------------------------------------------
# Half-open stall detection pins (acceptance): relay upstream + broker probe
# ---------------------------------------------------------------------------


class TestRelayStallHalfOpen:
    def test_stalled_upstream_detected_within_keepalive_bound(
        self, tmp_path
    ):
        turns = 300
        ka = 0.5
        plane = ServePlane(
            ServeConfig(max_sessions=2), checkpoint_root=tmp_path / "c"
        )
        gw = GatewayServer(plane, port=0)
        proxy = relay = drain = None
        try:
            client = GolClient(gw.url)
            submit_via(
                client, "alice", spec_doc(turns, 17, spectate=True)
            )
            pause_session(gw, "alice")
            # The relay's FIRST upstream leg goes half-open just past
            # the upgrade (the ~129-byte handshake response), inside
            # the hello — the classic silent half-open: TCP happy,
            # peer never speaks again.
            proxy = ChaosProxy(
                (gw.host, gw.port),
                WirePlan([WireFault(0, "stall", after_bytes=200)]),
                hang_seconds=60.0,
            )
            relay = RelayServer(
                proxy.url + f"/v1/sessions/alice/frames?queue={turns + 8}",
                cache_deltas=turns + 16,
                queue_depth=turns + 8,
                backoff_initial=0.05,
                backoff_max=0.2,
                connect_timeout=5.0,
                keepalive_seconds=ka,
                registry=obs_metrics.REGISTRY,
            )
            base_drops = counter("net.keepalive_drops")
            base_resub = counter("relay.resubscribes")
            # The stall strikes inside the hello, right after connect.
            wait_for(
                lambda: proxy.stalled_connections() == 1,
                30.0,
                "stall to strike",
            )
            t0 = time.monotonic()
            wait_for(
                lambda: counter("net.keepalive_drops") > base_drops,
                ka * 3 + 5.0,
                "keepalive drop",
            )
            detect = time.monotonic() - t0
            assert detect <= ka * 3 + 2.0, (
                f"half-open detection took {detect:.2f}s "
                f"(budget {ka * 3:.2f}s + 2s slack)"
            )
            # Recovery: the clean second connection carries the whole
            # stream end to end, bit-exact.
            wait_for(
                lambda: proxy.connections >= 2
                and relay.health()["connected"],
                30.0,
                "resubscribe on a clean connection",
            )
            drain = StreamDrain(
                relay.host, relay.port, "/v1/frames?queue=4096"
            ).start()
            client.resume("alice")
            drain.join(120.0)
            assert drain.ended
            assert drain.turn == turns
            assert np.array_equal(
                drain.buf, final_board(client, "alice", W)
            )
            assert counter("relay.resubscribes") > base_resub
        finally:
            if drain is not None and drain.thread.is_alive():
                drain.thread.join(5.0)
            if relay is not None:
                relay.close()
            if proxy is not None:
                proxy.close()
            gw.close()
            plane.close()


class TestBrokerProbeStall:
    def test_stalled_probe_condemns_within_probe_bound(self, tmp_path):
        interval, probe_timeout, misses = 0.1, 0.5, 2
        plane = ServePlane(
            ServeConfig(max_sessions=2), checkpoint_root=tmp_path / "c"
        )
        gw = GatewayServer(plane, port=0)
        proxy = ChaosProxy((gw.host, gw.port), hang_seconds=2.0)
        broker = None
        try:
            broker = Broker(
                [proxy.url],
                BrokerConfig(
                    probe_interval_seconds=interval,
                    probe_timeout_seconds=probe_timeout,
                    probe_miss_threshold=misses,
                    rejoin_threshold=2,
                ),
            )
            wait_for(
                lambda: broker.pod_states()[0]["ready"],
                30.0,
                "pod ready via probes",
            )
            base = counter("broker.pods_condemned")
            # Every probe connection from NOW stalls half-open (the
            # probe's read deadline, not TCP, must notice).
            proxy.set_plan(
                WirePlan([WireFault(i, "stall") for i in range(6)]),
                relative=True,
            )
            t0 = time.monotonic()
            wait_for(
                lambda: counter("broker.pods_condemned") > base,
                misses * (interval + probe_timeout) + 10.0,
                "condemnation",
            )
            detect = time.monotonic() - t0
            assert detect <= misses * (interval + probe_timeout) + 2.0, (
                f"probe-stall detection took {detect:.2f}s (budget "
                f"{misses * (interval + probe_timeout):.2f}s + 2s slack)"
            )
            # The stall burst exhausts; healthy probes rejoin the pod.
            wait_for(
                lambda: broker.pod_states()[0]["ready"]
                and not broker.pod_states()[0]["condemned"],
                30.0,
                "pod rejoin after the burst",
            )
        finally:
            if broker is not None:
                broker.close()
            proxy.close()
            gw.close()
            plane.close()


# ---------------------------------------------------------------------------
# Socket-hygiene lint (satellite): tier-1 gate, both directions
# ---------------------------------------------------------------------------


class TestSocketHygiene:
    def test_repo_is_clean(self):
        from tools import check_socket_hygiene

        assert check_socket_hygiene.check(REPO) == []

    def test_checker_catches_drift_both_directions(self, tmp_path):
        from tools import check_socket_hygiene

        pkg = tmp_path / "distributed_gol_tpu"
        pkg.mkdir()
        (tmp_path / "tools").mkdir()
        (pkg / "mod.py").write_text(
            "import socket\n"
            "conn = socket.create_connection((host, port))\n"
        )
        problems = check_socket_hygiene.check(tmp_path)
        assert any("undeadlined socket" in p for p in problems)
        assert any("stale allowlist entry" in p for p in problems)

        # Deadline the site and reinstate the allowlisted line: clean.
        (pkg / "mod.py").write_text(
            "import socket\n"
            "conn = socket.create_connection((host, port), timeout=5)\n"
        )
        par = pkg / "parallel"
        par.mkdir()
        (par / "multihost.py").write_text(
            "import socket\n"
            "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
        )
        assert check_socket_hygiene.check(tmp_path) == []

    def test_cli_entrypoint_reports_clean(self):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "check_socket_hygiene.py")],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert "socket hygiene clean" in proc.stdout


# ---------------------------------------------------------------------------
# The chaos matrix (tentpole acceptance): broker + 2 pods + depth-2
# relay chain, EVERY hop behind a seeded proxy — bit-identical finals,
# bounded health, no leaks.
# ---------------------------------------------------------------------------


class TestChaosMatrix:
    def test_full_cluster_converges_under_wire_chaos(self, tmp_path):
        # Control hops (client→broker, broker→pod A/B) take the full
        # fault alphabet at request-sized offsets; the relay hops skip
        # trickle (a per-byte crawl on a multi-KB frame stream) and
        # strike at byte 120 — inside the 129-byte WS handshake
        # response, so every breaking fault lands mid-handshake.
        CONTROL = dict(
            p_fault=0.3,
            kinds=("latency", "trickle", "disconnect", "corrupt", "stall"),
            seconds=0.003,
            after_bytes=200,
        )
        # Relay-hop faults are all BREAKING ones: a non-breaking fault
        # (latency) would park the relay mid-burst — with the stream
        # paused nothing ever disturbs a live connection, so it would
        # never advance past the remaining scheduled faults.
        RELAY = dict(
            p_fault=1.0,
            kinds=("stall", "disconnect", "corrupt"),
            seconds=0.0005,
            after_bytes=120,
        )
        BREAKING = ("stall", "disconnect", "corrupt")

        def settled(proxy, plan):
            """The proxy's CURRENT connection (= connections - 1; the
            relay is its only client) is past every breaking fault."""
            last = max(
                (f.at for f in plan.faults if f.kind in BREAKING),
                default=-1,
            )
            return proxy.connections - 1 > last

        alice_spec = spec_doc(600, 5, spectate=True)
        bob_spec = spec_doc(600, 9)
        carol_spec = spec_doc(500, 13)

        baseline_threads = threading.active_count()
        stack = []

        def push(obj):
            stack.append(obj)
            return obj

        # Health watchdog: every plane answers /healthz (via its
        # DIRECT url — the bound is on the server, not the chaos) in
        # under 2 s for the whole run.
        watch_stop = threading.Event()
        watch_urls = []
        watch_worst = [0.0]
        watch_failures = []

        def watchdog():
            while not watch_stop.is_set():
                for url in list(watch_urls):
                    t0 = time.monotonic()
                    try:
                        try:
                            with urllib.request.urlopen(
                                f"{url}/healthz", timeout=2.0
                            ):
                                pass
                        except urllib.error.HTTPError:
                            pass  # 503-with-a-body is an answer
                    except Exception as exc:  # noqa: BLE001
                        watch_failures.append(f"{url}: {exc!r}")
                    dt = time.monotonic() - t0
                    watch_worst[0] = max(watch_worst[0], dt)
                watch_stop.wait(0.25)

        watch_thread = threading.Thread(
            target=watchdog, name="test-healthz-watchdog", daemon=True
        )

        try:
            # -- the cluster, every hop proxied ---------------------------
            # Pod A gets the most headroom: placement sorts on free
            # capacity, so alice (the relay leg's tenant) lands there.
            plane_a = push(
                ServePlane(
                    ServeConfig(max_sessions=4),
                    checkpoint_root=tmp_path / "ca",
                )
            )
            gw_a = push(GatewayServer(plane_a, port=0))
            plane_b = push(
                ServePlane(
                    ServeConfig(max_sessions=4, max_total_cells=300_000),
                    checkpoint_root=tmp_path / "cb",
                )
            )
            gw_b = push(GatewayServer(plane_b, port=0))
            proxy_a = push(
                ChaosProxy(
                    (gw_a.host, gw_a.port),
                    WirePlan.random(101, 4096, **CONTROL),
                    hang_seconds=1.0,
                )
            )
            proxy_b = push(
                ChaosProxy(
                    (gw_b.host, gw_b.port),
                    WirePlan.random(202, 4096, **CONTROL),
                    hang_seconds=1.0,
                )
            )
            broker = push(
                Broker(
                    [proxy_a.url, proxy_b.url],
                    BrokerConfig(
                        probe_interval_seconds=0.2,
                        probe_timeout_seconds=1.0,
                        probe_miss_threshold=8,
                        rejoin_threshold=1,
                        request_timeout_seconds=10.0,
                        connect_timeout_seconds=2.0,
                        attempts=3,
                        backoff_seconds=0.05,
                        backoff_max_seconds=0.2,
                        failover=False,
                    ),
                )
            )
            proxy_c = push(
                ChaosProxy(
                    (broker.host, broker.port),
                    WirePlan.random(303, 4096, **CONTROL),
                    hang_seconds=1.0,
                )
            )
            client = GolClient(proxy_c.url, timeout=5.0, connect_timeout=3.0)
            direct_a = GolClient(gw_a.url)

            watch_urls.extend([gw_a.url, gw_b.url, broker.url])
            watch_thread.start()

            wait_for(
                lambda: all(p["ready"] for p in broker.pod_states()),
                60.0,
                "both pods ready through chaotic probes",
            )

            # -- submissions through the chaotic control path -------------
            # A spectate run with no subscriber burns thousands of
            # turns per second; the watcher pauses alice within a few
            # turns of creation so the relay leg has a stream to join.
            paused_evt = threading.Event()

            def pause_watcher():
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    s = gw_a._sessions.get("alice")
                    if s is not None:
                        try:
                            s.pause()
                        except Exception:  # noqa: BLE001
                            pass
                        if getattr(s, "paused", False):
                            paused_evt.set()
                            return
                    time.sleep(0.002)

            pw = threading.Thread(
                target=pause_watcher, name="test-pause-watcher",
                daemon=True,
            )
            pw.start()

            chaos_submit(client, "alice", alice_spec)
            assert paused_evt.wait(30.0), "alice never paused"
            wait_for(
                lambda: any(
                    "alice" in p["placed"] and p["endpoint"] == proxy_a.url
                    for p in broker.pod_states()
                ),
                30.0,
                "alice placed on pod A",
            )
            chaos_submit(client, "bob", bob_spec)
            chaos_submit(client, "carol", carol_spec)

            # -- the depth-2 relay chain, both hops chaotic ---------------
            plan_f1 = WirePlan.random(404, 6, **RELAY)
            plan_f2 = WirePlan.random(505, 6, **RELAY)
            proxy_f1 = push(
                ChaosProxy(
                    (gw_a.host, gw_a.port), plan_f1, hang_seconds=1.5
                )
            )
            r1 = push(
                RelayServer(
                    proxy_f1.url + "/v1/sessions/alice/frames?queue=1024",
                    cache_deltas=1400,
                    queue_depth=1024,
                    backoff_initial=0.05,
                    backoff_max=0.2,
                    connect_timeout=3.0,
                    keepalive_seconds=1.0,
                    registry=obs_metrics.REGISTRY,
                )
            )
            proxy_f2 = push(
                ChaosProxy((r1.host, r1.port), plan_f2, hang_seconds=1.5)
            )
            r2 = push(
                RelayServer(
                    proxy_f2.url + "/v1/frames?queue=1024",
                    cache_deltas=1400,
                    queue_depth=1024,
                    backoff_initial=0.05,
                    backoff_max=0.2,
                    connect_timeout=3.0,
                    keepalive_seconds=1.0,
                    registry=obs_metrics.REGISTRY,
                )
            )
            watch_urls.extend([r1.url, r2.url])

            # Both relays fight through their 6-connection fault burst
            # and settle on a clean steady-state connection BEFORE the
            # run resumes (a resubscribe after session end would never
            # re-anchor: keyframes only ride published turns).
            wait_for(
                lambda: r1.health()["connected"]
                and settled(proxy_f1, plan_f1),
                90.0,
                "relay 1 settled past its fault burst",
            )
            wait_for(
                lambda: r2.health()["connected"]
                and settled(proxy_f2, plan_f2),
                90.0,
                "relay 2 settled past its fault burst",
            )

            drain = StreamDrain(
                r2.host, r2.port, "/v1/frames?queue=4096"
            ).start()
            direct_a.resume("alice")

            # -- convergence ----------------------------------------------
            for tenant in ("alice", "bob", "carol"):
                wait_for(
                    lambda t=tenant: (
                        (broker_state(client, t) or {}).get("status")
                        == "completed"
                    ),
                    120.0,
                    f"{tenant} completed through the chaotic path",
                )
            drain.join(120.0)
            assert drain.ended
            assert drain.turn == 600

            # Bit-identity against the fault-free oracle, all tenants.
            alice_fb = final_board(direct_a, "alice", W)
            assert np.array_equal(drain.buf, alice_fb)
            oracle_alice = oracle_final(tmp_path, "alice", alice_spec)
            assert np.array_equal(
                alice_fb, event_board(oracle_alice.item(), W)
            )
            for tenant, spec in (
                ("bob", bob_spec), ("carol", carol_spec)
            ):
                handle = plane_a.handle(tenant) or plane_b.handle(tenant)
                assert handle is not None, f"{tenant} on neither pod"
                assert np.array_equal(
                    np.asarray(handle.final),
                    oracle_final(tmp_path, tenant, spec),
                )

            # Chaos actually struck, across hops and kinds.
            all_proxies = (
                proxy_a, proxy_b, proxy_c, proxy_f1, proxy_f2
            )
            fired = [f for p in all_proxies for f in p.fired]
            assert len(fired) >= 5, f"chaos barely fired: {fired}"
            assert len({f.kind for f in fired}) >= 3
            assert len(proxy_f1.fired) >= 1

            # Health stayed bounded the whole run.
            watch_stop.set()
            watch_thread.join(5.0)
            assert not watch_failures, watch_failures[:5]
            assert watch_worst[0] < 2.0, (
                f"worst /healthz answer {watch_worst[0]:.2f}s"
            )

            # -- teardown + leak pin --------------------------------------
            while stack:
                stack.pop().close()
            wait_for(
                lambda: chaos_threads() == [],
                20.0,
                "chaos proxy threads to drain",
            )
            for p in all_proxies:
                assert p.open_connections() == 0
            wait_for(
                lambda: threading.active_count()
                <= baseline_threads + 4,
                20.0,
                f"thread count to settle (baseline {baseline_threads}, "
                f"now {threading.active_count()})",
            )
        finally:
            watch_stop.set()
            while stack:
                try:
                    stack.pop().close()
                except Exception:  # noqa: BLE001
                    pass
