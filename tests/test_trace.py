"""Tracing/profiling harness — the ``trace_test.go`` port.

The reference's TestTrace (trace_test.go:12-29) is not an assertion but a
harness: wrap a 64²x10 run in runtime/trace and produce trace.out.  The TPU
analog wraps a run in the JAX profiler (``utils/profiling.trace``) and emits
per-dispatch ``TurnTiming`` events; here we assert both hooks actually fire.
"""

import queue

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.utils.profiling import has_trace_output, trace


def _run(params):
    ev = queue.Queue()
    gol.run(params, ev)
    out = []
    while (e := ev.get(timeout=60)) is not None:
        out.append(e)
    return out


def _params(tmp_path, input_images, **kw):
    return gol.Params(
        turns=10,
        image_width=64,
        image_height=64,
        images_dir=input_images,
        out_dir=tmp_path,
        **kw,
    )


def test_profiler_trace_produces_output(tmp_path, input_images):
    """A traced run writes profiler artifacts (trace_test.go's trace.out
    analog); skipped only if this jax build lacks a profiler backend."""
    log_dir = tmp_path / "trace"
    with trace(log_dir):
        _run(_params(tmp_path, input_images))
    if not has_trace_output(log_dir):
        pytest.skip("jax profiler backend unavailable in this build")


def test_turn_timing_events(tmp_path, input_images):
    events = _run(_params(tmp_path, input_images, emit_timing=True, superstep=5))
    timings = [e for e in events if isinstance(e, gol.TurnTiming)]
    assert len(timings) == 2  # 10 turns / superstep 5
    assert [t.turns for t in timings] == [5, 5]
    assert [t.completed_turns for t in timings] == [5, 10]
    assert all(t.seconds > 0 for t in timings)
    assert all(t.gens_per_sec > 0 for t in timings)
    assert "turns in" in str(timings[0])


def test_no_timing_by_default(tmp_path, input_images):
    events = _run(_params(tmp_path, input_images))
    assert not [e for e in events if isinstance(e, gol.TurnTiming)]


def test_profiler_unavailable_warns_scoped(tmp_path, monkeypatch):
    """An unavailable profiler degrades to an untraced run via a SCOPED
    RuntimeWarning — not a bare stderr print that bypasses the warning
    policy (pytest escalates it to an error when uncaptured; pinned
    round-7 satellite)."""
    import jax

    def broken(log_dir):
        raise RuntimeError("no profiler backend")

    monkeypatch.setattr(jax.profiler, "trace", broken)
    ran = []
    with pytest.warns(RuntimeWarning, match="profiler unavailable"):
        with trace(tmp_path / "trace"):
            ran.append(True)  # the run itself continues untraced
    assert ran == [True]
