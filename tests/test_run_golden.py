"""Port of the reference's TestGol + TestPgm (gol_test.go, pgm_test.go).

Black-box against the public run() + event-stream contract: the final
FinalTurnComplete.alive multiset and the written out/WxHxT.pgm file must
match the golden images for {16², 64², 512²} × {0, 1, 100} turns.  The
reference also sweeps threads 1..16 (144 subtests) because threads changed
its goroutine split; here XLA owns intra-chip parallelism, so the knob is
accepted-and-recorded — a reduced sweep asserts it doesn't change results.
Unlike the reference (which needs a live AWS cluster), these run hermetically.
"""

import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.pgm import read_pgm
from distributed_gol_tpu.utils.visualise import boards_to_string
from distributed_gol_tpu.utils.cell import board_from_alive_cells

SIZES = [16, 64, 512]
TURNS = [0, 1, 100]


def drain(events: queue.Queue):
    seen = []
    while True:
        e = events.get(timeout=60)
        if e is None:
            return seen
        seen.append(e)


def run_and_collect(params):
    events = queue.Queue()
    gol.run(params, events)
    return drain(events)


def make_params(size, turns, tmp_path, input_images, **kw):
    return gol.Params(
        turns=turns,
        image_width=size,
        image_height=size,
        images_dir=input_images,
        out_dir=tmp_path,
        **kw,
    )


def assert_equal_board(alive, golden_board, size):
    """Order-insensitive comparison of the alive-cell list vs the golden
    board (the reference's assertEqualBoard, gol_test.go:58-86)."""
    got = board_from_alive_cells(alive, size, size)
    if not np.array_equal(got, golden_board):
        if size == 16:
            pytest.fail("final board mismatch:\n" + boards_to_string(golden_board, got))
        pytest.fail(
            f"final board mismatch: {np.count_nonzero(got)} alive vs "
            f"{np.count_nonzero(golden_board)} expected"
        )


@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("turns", TURNS)
def test_gol_final_board(size, turns, tmp_path, input_images, golden_images):
    events = run_and_collect(make_params(size, turns, tmp_path, input_images))
    finals = [e for e in events if isinstance(e, gol.FinalTurnComplete)]
    assert len(finals) == 1
    assert finals[0].completed_turns == turns  # quirk Q1 fixed: true count
    golden = read_pgm(golden_images / f"{size}x{size}x{turns}.pgm")
    assert_equal_board(finals[0].alive, golden, size)


@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("turns", TURNS)
def test_pgm_output_file(size, turns, tmp_path, input_images, golden_images):
    run_and_collect(make_params(size, turns, tmp_path, input_images))
    written = (tmp_path / f"{size}x{size}x{turns}.pgm").read_bytes()
    golden = (golden_images / f"{size}x{size}x{turns}.pgm").read_bytes()
    assert written == golden  # byte-identical, incl. header


@pytest.mark.parametrize("threads", [1, 8, 16])
def test_threads_knob_is_inert(threads, tmp_path, input_images, golden_images):
    """The reference's thread sweep: results must not depend on it."""
    events = run_and_collect(
        make_params(16, 100, tmp_path, input_images, threads=threads)
    )
    final = [e for e in events if isinstance(e, gol.FinalTurnComplete)][0]
    golden = read_pgm(golden_images / "16x16x100.pgm")
    assert_equal_board(final.alive, golden, 16)


@pytest.mark.parametrize("superstep", [1, 7, 100])
def test_superstep_does_not_change_results(
    superstep, tmp_path, input_images, golden_images
):
    """Supersteps are a dispatch-granularity knob, never a semantics knob."""
    events = run_and_collect(
        make_params(64, 100, tmp_path, input_images, superstep=superstep)
    )
    final = [e for e in events if isinstance(e, gol.FinalTurnComplete)][0]
    golden = read_pgm(golden_images / "64x64x100.pgm")
    assert_equal_board(final.alive, golden, 64)
    turn_completes = [e for e in events if isinstance(e, gol.TurnComplete)]
    assert [e.completed_turns for e in turn_completes] == list(range(1, 101))


@pytest.mark.parametrize("mesh_shape", [(2, 1), (2, 4), (8, 1)])
def test_sharded_run_matches_golden(mesh_shape, tmp_path, input_images, golden_images):
    """Full run over a virtual device mesh: halo exchange + psum counts
    produce byte-identical output (SURVEY.md §7 stage 4 bit-identity gate)."""
    events = run_and_collect(
        make_params(64, 100, tmp_path, input_images, mesh_shape=mesh_shape)
    )
    written = (tmp_path / "64x64x100.pgm").read_bytes()
    golden = (golden_images / "64x64x100.pgm").read_bytes()
    assert written == golden
    final = [e for e in events if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == 100


@pytest.mark.parametrize("mesh_shape", [(2, 1), (8, 1), (2, 4)])
def test_sharded_512_matches_golden(mesh_shape, tmp_path, input_images, golden_images):
    """The reference's own benchmark size, sharded: 512²×100 over virtual
    meshes, byte-identical final PGM (row meshes exercise the sharded
    pallas-packed path in interpret mode; (2, 4) the 2-D word-halo path)."""
    run_and_collect(
        make_params(512, 100, tmp_path, input_images, mesh_shape=mesh_shape)
    )
    written = (tmp_path / "512x512x100.pgm").read_bytes()
    golden = (golden_images / "512x512x100.pgm").read_bytes()
    assert written == golden


@pytest.mark.slow
@pytest.mark.parametrize("threads", range(1, 17))
@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("turns", TURNS)
def test_full_reference_matrix(
    threads, size, turns, tmp_path, input_images, golden_images
):
    """The reference's complete 144-subtest matrix (gol_test.go:29-31):
    {16², 64², 512²} × {0, 1, 100} turns × threads 1..16.  The threads knob
    is inert here by design (XLA owns intra-chip parallelism), so this is
    an inertness proof at full reference granularity; the fast suite keeps
    the 3-point sweep.  Run with ``pytest -m slow``."""
    events = run_and_collect(
        make_params(size, turns, tmp_path, input_images, threads=threads)
    )
    finals = [e for e in events if isinstance(e, gol.FinalTurnComplete)]
    assert len(finals) == 1
    golden = read_pgm(golden_images / f"{size}x{size}x{turns}.pgm")
    assert_equal_board(finals[0].alive, golden, size)
    written = (tmp_path / f"{size}x{size}x{turns}.pgm").read_bytes()
    assert written == (golden_images / f"{size}x{size}x{turns}.pgm").read_bytes()
