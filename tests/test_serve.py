"""The multi-tenant serving plane suite (ISSUE 6).

Four contracts, asserted hermetically on CPU:

- **Admission + backpressure**: the capacity budget's decision ladder
  (run -> bounded queue -> shed with retry-after) is deterministic in
  submission order, queue depth and memory stay bounded under a scripted
  flood (the `flood` fault kind), and a rejection is always explicit —
  never an unbounded wait.
- **Per-session fault isolation** (the chaos rows): one tenant under
  injected terminal faults — burst, corrupt, hang, flood — parks or
  sheds ALONE while >= 2 healthy tenants beside it complete
  bit-identically to their fault-free solo oracles.  No cross-tenant
  abort, no pod exit.
- **Graceful pod drain**: a real SIGTERM against a pod with N resident
  sessions emergency-checkpoints every one (fsync-durable), the process
  survives, and a fresh pod re-adopts each tenant to the oracle state.
- **Health surface + per-tenant obs labels**: one registry snapshot
  separates tenants via their ``tenant=`` labels (DispatchRecorder,
  checkpoint sidecars, MetricsReport), and ``health()`` exposes the
  readiness/liveness an external balancer needs.

Chaos rows are marked ``chaos`` like the rest of the matrix.
"""

import json
import os
import queue
import signal
import threading
import time

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.controller import DispatchTimeout
from distributed_gol_tpu.engine.events import DispatchError
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.serve import (
    AdmissionController,
    AdmissionRejected,
    ServeConfig,
    ServePlane,
)
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
    FloodTenant,
)

# One pod workload shape for the whole suite: small boards, explicit
# superstep, no cycle check — the dispatch schedule (= fault indices) is
# exact and identical between a plane-multiplexed run and its solo oracle.
W = H = 16
SUPERSTEP = 4
TURNS = 24


def tenant_params(out_dir, seed, turns=TURNS, **kw):
    cfg = dict(
        engine="roll",
        mesh_shape=(1, 1),
        image_width=W,
        image_height=H,
        superstep=SUPERSTEP,
        turns=turns,
        soup_density=0.25,
        soup_seed=seed,
        out_dir=out_dir,
        cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return Params(**cfg)


@pytest.fixture(scope="module")
def solo_oracle(tmp_path_factory):
    """Fault-free solo run per soup seed, computed once: the final board
    bytes every healthy multiplexed tenant must match bit-identically."""
    cache = {}

    def get(seed):
        if seed not in cache:
            out = tmp_path_factory.mktemp(f"solo-{seed}")
            p = tenant_params(out, seed)
            events: queue.Queue = queue.Queue()
            gol.run(p, events)
            while events.get(timeout=60) is not None:
                pass
            cache[seed] = (out / f"{p.final_output_name}.pgm").read_bytes()
        return cache[seed]

    return get


def assert_healthy_matches_oracle(handle, solo_oracle, seed):
    assert handle.status == "completed", (
        f"healthy tenant {handle.tenant} did not complete: "
        f"{handle.status} ({handle.error})"
    )
    assert handle.final is not None
    assert handle.final.completed_turns == handle.params.turns
    got = (
        handle.params.out_dir / f"{handle.params.final_output_name}.pgm"
    ).read_bytes()
    assert got == solo_oracle(seed), (
        f"healthy tenant {handle.tenant} diverged from its solo oracle"
    )


# -- admission control units (pure bookkeeping, no device work) ----------------


class TestServeConfig:
    def test_defaults_are_valid(self):
        ServeConfig()

    @pytest.mark.parametrize(
        "field, bad",
        [
            ("max_sessions", 0),
            ("max_queued", -1),
            ("max_cells_per_session", 0),
            ("max_total_cells", -1),
            ("default_deadline_seconds", -0.5),
            ("retry_after_seconds", -1.0),
            ("drain_timeout_seconds", 0.0),
        ],
    )
    def test_rejects_bad_budget(self, field, bad):
        with pytest.raises(ValueError):
            ServeConfig(**{field: bad})


class TestAdmissionController:
    CFG = ServeConfig(
        max_sessions=2,
        max_queued=2,
        max_cells_per_session=100,
        max_total_cells=500,
        retry_after_seconds=2.5,
    )

    def test_decision_ladder_is_deterministic(self):
        """run, run, queue, queue, shed — a pure function of the
        submission order, down to the retry-after hint."""
        ac = AdmissionController(self.CFG)
        assert ac.admit("a", 10) == "run"
        assert ac.admit("b", 10) == "run"
        assert ac.admit("c", 10) == "queue"
        assert ac.admit("d", 10) == "queue"
        with pytest.raises(AdmissionRejected) as ei:
            ac.admit("e", 10)
        assert ei.value.retry_after == 2.5
        assert ac.queued == 2 and len(ac.resident) == 2

    def test_oversized_board_is_a_permanent_rejection(self):
        ac = AdmissionController(self.CFG)
        with pytest.raises(AdmissionRejected) as ei:
            ac.admit("big", 101)
        assert ei.value.retry_after is None  # retrying the same ask is futile
        assert not ac.resident and not ac.waiting

    def test_pod_cell_budget_frees_on_release(self):
        """A pod-budget rejection is transient: releasing a resident
        session frees its cells and the same submission then admits."""
        cfg = ServeConfig(
            max_sessions=4, max_queued=4, max_cells_per_session=100,
            max_total_cells=150,
        )
        ac = AdmissionController(cfg)
        assert ac.admit("a", 100) == "run"
        with pytest.raises(AdmissionRejected):
            ac.admit("b", 100)
        ac.release("a")
        assert ac.admit("b", 100) == "run"

    def test_pod_cell_budget_rejects_with_retry_after(self):
        cfg = ServeConfig(
            max_sessions=4, max_queued=4, max_cells_per_session=100,
            max_total_cells=150, retry_after_seconds=1.0,
        )
        ac = AdmissionController(cfg)
        assert ac.admit("a", 100) == "run"
        with pytest.raises(AdmissionRejected) as ei:
            ac.admit("b", 100)
        assert ei.value.retry_after == 1.0
        # Queued cells count against the budget too (a queued board WILL
        # become resident: admitting past the budget just defers the OOM).
        assert ac.total_cells == 100

    def test_degraded_capacity_scales_the_pod_budget(self):
        """ISSUE 7: a capacity factor below 1.0 (the healthy share of the
        pod's devices, synced from the mesh blacklist by the plane)
        shrinks the effective pod cell budget — admission sheds against
        what the surviving silicon can hold, and the rejection names the
        degradation.  An unbounded pod (max_total_cells=0) keeps that
        choice while degraded."""
        cfg = ServeConfig(
            max_sessions=4, max_queued=4, max_cells_per_session=100,
            max_total_cells=200, retry_after_seconds=1.0,
        )
        ac = AdmissionController(cfg)
        assert ac.effective_total_cells == 200
        ac.capacity_factor = 0.5  # half the devices condemned
        assert ac.effective_total_cells == 100
        assert ac.admit("a", 100) == "run"
        with pytest.raises(AdmissionRejected, match="degraded: 50%"):
            ac.admit("b", 100)  # fits the full budget, not the degraded one
        ac.capacity_factor = 1.0
        assert ac.admit("b", 100) == "run"
        unbounded = AdmissionController(
            ServeConfig(
                max_sessions=4, max_queued=4, max_cells_per_session=100,
                max_total_cells=0,
            )
        )
        unbounded.capacity_factor = 0.25
        assert unbounded.effective_total_cells == 0  # 0 stays unbounded

    def test_duplicate_tenant_is_shed(self):
        ac = AdmissionController(self.CFG)
        ac.admit("a", 10)
        with pytest.raises(AdmissionRejected, match="live session"):
            ac.admit("a", 10)

    def test_promotion_is_fifo(self):
        ac = AdmissionController(self.CFG)
        for t in ("a", "b", "c", "d"):
            ac.admit(t, 10)
        ac.release("a")
        assert ac.pop_waiting() == ("c", 10)  # longest-waiting first
        assert ac.pop_waiting() is None  # pod full again
        ac.release("b")
        assert ac.pop_waiting() == ("d", 10)

    def test_drain_closes_admissions_and_sheds_the_queue(self):
        ac = AdmissionController(self.CFG)
        for t in ("a", "b", "c"):
            ac.admit(t, 10)
        ac.draining = True
        with pytest.raises(AdmissionRejected) as ei:
            ac.admit("d", 10)
        assert ei.value.retry_after is None  # this pod is going away
        assert ac.shed_waiting() == ["c"]
        assert not ac.has_room()


# -- the plane: happy path, scheduling, backpressure ---------------------------


class TestPlaneBasics:
    def test_sessions_complete_and_digest(self, tmp_path, solo_oracle):
        with ServePlane(ServeConfig(max_sessions=2)) as plane:
            h1 = plane.submit("alice", tenant_params(tmp_path / "alice", 1))
            h2 = plane.submit("bob", tenant_params(tmp_path / "bob", 2))
            assert plane.wait_idle(timeout=120)
        for h, seed in ((h1, 1), (h2, 2)):
            assert_healthy_matches_oracle(h, solo_oracle, seed)
            assert h.last_turn == TURNS
            assert h.report is not None  # MetricsReport digested
            assert not h.resumable  # completed runs leave nothing parked
            assert h.duration is not None and h.duration > 0

    def test_queued_session_is_promoted_fifo(self, tmp_path, solo_oracle):
        """One slot, three tenants: all complete (in admission order),
        each bit-identical to its solo oracle."""
        with ServePlane(ServeConfig(max_sessions=1, max_queued=2)) as plane:
            handles = [
                plane.submit(f"t{i}", tenant_params(tmp_path / f"t{i}", i))
                for i in range(3)
            ]
            assert handles[0].admitted_as == "run"
            assert handles[1].admitted_as == "queue"
            assert handles[2].admitted_as == "queue"
            assert plane.wait_idle(timeout=180)
        for i, h in enumerate(handles):
            assert_healthy_matches_oracle(h, solo_oracle, i)
        # Queue wait ordering: t1 started no later than t2.
        assert handles[1].t_start <= handles[2].t_start

    def test_submit_never_blocks_and_sheds_explicitly(self, tmp_path):
        with ServePlane(ServeConfig(max_sessions=1, max_queued=1)) as plane:
            plane.submit("a", tenant_params(tmp_path / "a", 1, turns=10**6))
            plane.submit("b", tenant_params(tmp_path / "b", 2))
            t0 = time.monotonic()
            with pytest.raises(AdmissionRejected) as ei:
                plane.submit("c", tenant_params(tmp_path / "c", 3))
            assert time.monotonic() - t0 < 5  # immediate, not a timeout
            assert ei.value.retry_after is not None
            plane.begin_drain()
        assert plane.handle("a").status in ("drained", "completed")
        assert plane.handle("b").status in ("shed", "drained", "completed")

    def test_caller_owned_event_stream_is_teed_not_consumed(self, tmp_path):
        """The caller keeps every event of their own queue, AND the
        plane's digest still populates (producer-side tee) — so the
        drain receipt / classification work in bring-your-own-queue
        mode too."""
        events: queue.Queue = queue.Queue()
        with ServePlane(ServeConfig(max_sessions=1)) as plane:
            h = plane.submit(
                "a", tenant_params(tmp_path / "a", 1), events=events
            )
            seen = []
            while (e := events.get(timeout=60)) is not None:
                seen.append(e)
            assert h.wait(timeout=60)
        assert h.status == "completed"
        finals = [e for e in seen if isinstance(e, gol.FinalTurnComplete)]
        assert finals and finals[0].completed_turns == TURNS
        # The digest saw the same stream the caller did.
        assert h.final is not None and h.final.completed_turns == TURNS
        assert h.last_turn == TURNS
        turns = [e for e in seen if isinstance(e, gol.TurnComplete)]
        assert len(turns) == TURNS  # caller missed nothing to the tee

    def test_deadline_propagates_into_the_watchdog(self, tmp_path):
        p = tenant_params(tmp_path / "a", 1)
        assert p.dispatch_deadline_seconds == 0
        with ServePlane(
            ServeConfig(max_sessions=1, default_deadline_seconds=30.0)
        ) as plane:
            h = plane.submit("a", p)
            h2_deadline = plane.submit(
                "b", tenant_params(tmp_path / "b", 2), deadline_seconds=45.0
            )
            assert plane.wait_idle(timeout=120)
        assert h.params.dispatch_deadline_seconds == 30.0  # config default
        assert h2_deadline.params.dispatch_deadline_seconds == 45.0  # wins
        assert h.status == h2_deadline.status == "completed"

    def test_params_own_deadline_not_clobbered_by_config_default(
        self, tmp_path
    ):
        """The config default applies only to sessions WITHOUT their own
        deadline — a tenant that configured a generous watchdog must not
        have it silently tightened by the pod's default."""
        p = tenant_params(tmp_path / "a", 1, dispatch_deadline_seconds=300.0)
        with ServePlane(
            ServeConfig(max_sessions=1, default_deadline_seconds=30.0)
        ) as plane:
            h = plane.submit("a", p)
            assert plane.wait_idle(timeout=120)
        assert h.params.dispatch_deadline_seconds == 300.0
        assert h.status == "completed"

    def test_completed_before_drain_not_reported_drained(self, tmp_path):
        """A session whose FinalTurnComplete covered all its turns is
        'completed' even when the drain latch was raised concurrently —
        the receipt must not claim an interrupted, non-resumable tenant
        where there is a finished one."""
        from distributed_gol_tpu.serve.plane import SessionHandle

        p = tenant_params(tmp_path / "a", 1)
        with ServePlane(ServeConfig(max_sessions=1)) as plane:
            h = SessionHandle("a", p, Session(), queue.Queue(), True)
            h.t_start = time.perf_counter()
            h.final = gol.FinalTurnComplete(completed_turns=p.turns)
            h.last_turn = p.turns
            h.stop.request()  # drain latched just as the run finished
            plane._classify(h, None)
        assert h.status == "completed"
        assert h.last_turn == TURNS

    def test_drain_receipt_turn_with_caller_owned_stream(self, tmp_path):
        """submit(events=...) means the plane never sees TurnComplete —
        the drain receipt's turn must come from the parked checkpoint,
        not read 0."""
        ev = queue.Queue()
        plane = ServePlane(
            ServeConfig(max_sessions=1), checkpoint_root=tmp_path / "ckpt"
        )
        try:
            h = plane.submit(
                "a",
                tenant_params(tmp_path / "a", 1, turns=10**6),
                events=ev,
            )
            # Wait for real progress via the caller-owned stream.
            deadline = time.monotonic() + 60
            progressed = 0
            while time.monotonic() < deadline and progressed < SUPERSTEP:
                e = ev.get(timeout=30)
                if hasattr(e, "completed_turns"):
                    progressed = e.completed_turns
            receipt = plane.drain(timeout=60)
            while ev.get(timeout=30) is not None:  # caller drains to sentinel
                pass
        finally:
            plane.close()
        assert h.status == "drained" and h.resumable
        assert receipt["a"]["turn"] >= SUPERSTEP
        assert receipt["a"]["turn"] == h.session.parked_turn

    def test_terminal_handles_evicted_beyond_budget(self, tmp_path):
        """A pod serving churning tenant names stays bounded: beyond
        ``max_retained_handles`` the oldest terminal handle is evicted —
        introspection books AND the tenant's labelled registry
        instruments — while resident/queued handles are never touched."""
        with ServePlane(
            ServeConfig(max_sessions=1, max_retained_handles=2)
        ) as plane:
            names = [f"churn{i}" for i in range(5)]
            for i, name in enumerate(names):
                h = plane.submit(name, tenant_params(tmp_path / name, i + 1))
                assert h.wait(timeout=120)
            assert plane.wait_idle(timeout=60)
            retained = set(plane.health()["tenants"])
        assert retained == set(names[-2:])
        for gone in names[:-2]:
            assert plane.handle(gone) is None
        # The evicted tenants' labelled instruments left the registry.
        snap = obs_metrics.REGISTRY.snapshot(include_lazy=False).to_dict()
        live = {
            obs_metrics.tenant_of(k)
            for section in ("counters", "gauges", "histograms")
            for k in snap.get(section, {})
        }
        for gone in names[:-2]:
            assert gone not in live
        for kept in names[-2:]:
            assert kept in live

    def test_checkpoint_digest_is_bounded(self, tmp_path):
        """checkpoint_turns keeps the last 32 — an eternally-running
        tenant's digest must stay O(1) like the errors cap."""
        h = None
        with ServePlane(
            ServeConfig(max_sessions=1), checkpoint_root=tmp_path / "ckpt"
        ) as plane:
            h = plane.submit(
                "a",
                tenant_params(
                    tmp_path / "a",
                    1,
                    turns=40 * SUPERSTEP,
                    checkpoint_every_turns=SUPERSTEP,
                ),
            )
            assert plane.wait_idle(timeout=180)
        assert h.status == "completed"
        # 39 periodic saves (the final boundary completes + discards
        # instead of saving), digest capped to the LAST 32.
        assert len(h.checkpoint_turns) == 32
        assert list(h.checkpoint_turns)[-1] == 39 * SUPERSTEP
        assert list(h.checkpoint_turns)[0] == 8 * SUPERSTEP

    def test_tenant_name_mismatch_is_rejected(self, tmp_path):
        with ServePlane(ServeConfig()) as plane:
            with pytest.raises(ValueError, match="contradicts"):
                plane.submit(
                    "alice", tenant_params(tmp_path, 1, tenant="bob")
                )

    def test_health_surface(self, tmp_path):
        with ServePlane(ServeConfig(max_sessions=2, max_queued=1)) as plane:
            before = plane.health()
            assert before["ready"] and before["live"]
            assert before["resident_sessions"] == 0
            h = plane.submit("alice", tenant_params(tmp_path / "alice", 1))
            assert h.wait(timeout=120)
            assert plane.wait_idle(timeout=60)
            hl = plane.health()
            assert hl["tenants"]["alice"]["status"] == "completed"
            assert hl["tenants"]["alice"]["turns"] == TURNS
            assert hl["tenants"]["alice"]["dispatches"] == TURNS // SUPERSTEP
            assert hl["watchdog_fires"] == 0
            assert hl["capacity"]["max_sessions"] == 2
        after = plane.health()
        assert not after["ready"] and after["draining"]

    def test_degraded_pod_reports_and_admits_reduced_capacity(self, tmp_path):
        """ISSUE 7 serving-plane leg: once a device lands on the
        process-wide blacklist (a resident's elastic supervisor condemned
        it), ``health()`` reports ``degraded`` with the lost-device count
        and the scaled cell budget, and admission sheds against the
        reduced capacity.  A degraded pod stays ready — it just holds
        less."""
        import jax

        from distributed_gol_tpu.parallel import mesh as mesh_lib

        n = len(jax.devices())
        cells = W * H  # one tenant board
        try:
            with ServePlane(
                ServeConfig(
                    max_sessions=4, max_queued=0,
                    max_cells_per_session=cells,
                    max_total_cells=2 * cells,
                )
            ) as plane:
                healthy = plane.health()
                assert not healthy["degraded"] and healthy["devices_lost"] == 0
                assert healthy["capacity"]["effective_total_cells"] == 2 * cells

                # Half the rig dies: the budget falls below two boards.
                mesh_lib.condemn(range(n // 2, n))
                degraded = plane.health()
                assert degraded["degraded"] is True
                assert degraded["devices_lost"] == n - n // 2
                assert degraded["capacity"]["effective_total_cells"] == cells
                assert degraded["ready"]  # degraded, not dead

                h = plane.submit("alice", tenant_params(tmp_path / "a", 1))
                with pytest.raises(AdmissionRejected, match="degraded"):
                    plane.submit("bob", tenant_params(tmp_path / "b", 2))
                assert h.wait(timeout=120)
        finally:
            mesh_lib.clear_blacklist()


# -- per-tenant obs labels (satellite) -----------------------------------------


class TestTenantLabels:
    def test_labelled_roundtrip(self):
        assert obs_metrics.labelled("controller.turns", None) == "controller.turns"
        name = obs_metrics.labelled("controller.turns", "alice")
        assert name == "controller.turns{tenant=alice}"
        assert obs_metrics.tenant_of(name) == "alice"
        assert obs_metrics.tenant_of("controller.turns") is None

    @pytest.mark.parametrize("bad", ["", "a/b", "..", ".", "a" * 65, "a b"])
    def test_params_rejects_unsafe_tenant_names(self, bad, tmp_path):
        with pytest.raises(ValueError, match="tenant"):
            tenant_params(tmp_path, 1, tenant=bad)

    def test_one_snapshot_separates_tenants(self, tmp_path, solo_oracle):
        """Two tenants through one process-wide registry: each session's
        MetricsReport carries ITS OWN labelled dispatch counters, and an
        untenanted run keeps the exact pre-serving metric names."""
        with ServePlane(ServeConfig(max_sessions=2)) as plane:
            ha = plane.submit("alice", tenant_params(tmp_path / "a", 1))
            hb = plane.submit("bob", tenant_params(tmp_path / "b", 2))
            assert plane.wait_idle(timeout=120)
        for h, t in ((ha, "alice"), (hb, "bob")):
            counters = h.report.snapshot["counters"]
            key = f"controller.turns{{tenant={t}}}"
            assert counters[key] == TURNS
            assert (
                counters[f"controller.dispatches{{tenant={t}}}"]
                == TURNS // SUPERSTEP
            )
        # alice's report (a whole-registry delta over her run's window)
        # must not claim bob's turns as plain "controller.turns".
        assert ha.report.snapshot["counters"].get("controller.turns", 0) == 0

        # Untenanted control: exact pre-serving names, no labels.
        events: queue.Queue = queue.Queue()
        gol.run(tenant_params(tmp_path / "solo", 3), events)
        report = None
        while (e := events.get(timeout=60)) is not None:
            if isinstance(e, gol.MetricsReport):
                report = e
        assert report.snapshot["counters"]["controller.turns"] == TURNS
        assert not any(
            "{tenant=" in k for k in report.snapshot["counters"]
        )

    def test_checkpoint_sidecar_carries_tenant_labels(self, tmp_path):
        """The drain contract's postmortem trail: a parked tenant's
        sidecar snapshot separates that tenant's work by label."""
        plane = ServePlane(
            ServeConfig(max_sessions=1), checkpoint_root=tmp_path / "ckpt"
        )
        plane.submit(
            "alice",
            tenant_params(tmp_path / "out", 1, turns=10**6),
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if plane.handle("alice").last_turn >= SUPERSTEP:
                break
            time.sleep(0.05)
        plane.drain(timeout=60)
        plane.close()
        sidecars = list((tmp_path / "ckpt" / "alice").glob("checkpoint*.json"))
        assert sidecars, "drain parked no checkpoint"
        metas = [json.loads(p.read_text()) for p in sidecars]
        snaps = [m["metrics"] for m in metas if m.get("metrics")]
        assert snaps, "no sidecar embedded a metrics snapshot"
        assert any(
            obs_metrics.tenant_of(k) == "alice"
            for s in snaps
            for k in s.get("counters", {})
        )


# -- the chaos isolation matrix (tentpole leg 2) -------------------------------
#
# One faulty tenant beside TWO healthy ones, per fault kind.  The
# assertion is always the same shape: the healthy tenants complete
# bit-identical to their fault-free solo oracles, the sick tenant is
# parked-resumable / cleanly failed / shed — and the pod survives to
# serve the next submission.

pytestmark_chaos = pytest.mark.chaos

HEALTHY_SEEDS = (101, 202)


def submit_healthy(plane, tmp_path, pace_seconds=0.0):
    """Submit the two healthy tenants.  ``pace_seconds > 0`` gives each a
    latency-faulted backend (bit-identical; ~6x that long resident) so a
    test asserting on slot occupancy cannot race a healthy tenant
    completing on a warm-jit rig."""
    handles = []
    for i, seed in enumerate(HEALTHY_SEEDS):
        p = tenant_params(tmp_path / f"good{i}", seed)
        backend = None
        if pace_seconds:
            backend = FaultInjectionBackend(
                Backend(p),
                FaultPlan(
                    [Fault(k, "latency", seconds=pace_seconds) for k in range(6)]
                ),
            )
        handles.append(plane.submit(f"good{i}", p, backend=backend))
    return handles


def assert_pod_survives(plane, tmp_path, solo_oracle):
    """The no-cross-tenant-abort coda: the pod still admits and completes
    fresh work after the faulty tenant's demise."""
    h = plane.submit("after", tenant_params(tmp_path / "after", 303))
    assert h.wait(timeout=120)
    assert_healthy_matches_oracle(h, solo_oracle, 303)


@pytest.mark.chaos
class TestTenantIsolation:
    def test_burst_parks_one_tenant_alone(self, tmp_path, solo_oracle):
        """A 2-failure burst (terminal under the default retry budget)
        kills ONE tenant — parked resumable, error digested — while both
        healthy neighbours land on their oracles."""
        sick_params = tenant_params(tmp_path / "sick", 999)
        sick_backend = FaultInjectionBackend(
            Backend(sick_params),
            FaultPlan([Fault(2, "issue"), Fault(3, "issue")]),
        )
        with ServePlane(
            ServeConfig(max_sessions=3), checkpoint_root=tmp_path / "ckpt"
        ) as plane:
            healthy = submit_healthy(plane, tmp_path)
            sick = plane.submit("sick", sick_params, backend=sick_backend)
            assert plane.wait_idle(timeout=180)
            for h, seed in zip(healthy, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            assert sick.status == "parked"
            assert sick.resumable
            assert "RuntimeError" in sick.error
            errors = sick.errors
            assert [e.will_retry for e in errors] == [True, False]
            assert plane.health()["live"]
            assert_pod_survives(plane, tmp_path, solo_oracle)
        # Parked-resumable means exactly that: a fresh run on the sick
        # tenant's scoped session completes to ITS solo oracle.
        events: queue.Queue = queue.Queue()
        gol.run(
            tenant_params(tmp_path / "resumed", 999),
            events,
            session=Session(tmp_path / "ckpt" / "sick"),
        )
        while events.get(timeout=60) is not None:
            pass
        got = tmp_path / "resumed" / f"{W}x{H}x{TURNS}.pgm"
        assert got.read_bytes() == solo_oracle(999)

    def test_corrupt_tenant_self_heals_in_place(self, tmp_path, solo_oracle):
        """The supervised variant: a corrupt-fault tenant with its own
        restart ladder (SDC sentinel + rollback) RECOVERS to its oracle
        without any other tenant noticing — per-session supervision is
        per-session."""
        sick_params = tenant_params(
            tmp_path / "sick",
            999,
            checkpoint_every_turns=SUPERSTEP,
            sdc_check_every_turns=SUPERSTEP,
            restart_limit=2,
        )
        plan = FaultPlan([Fault(2, "corrupt", cells=3)])

        def factory(params, attempt):
            backend = Backend(params)
            return FaultInjectionBackend(backend, plan) if attempt == 0 else backend

        with ServePlane(ServeConfig(max_sessions=3)) as plane:
            healthy = submit_healthy(plane, tmp_path)
            sick = plane.submit("sick", sick_params, backend_factory=factory)
            assert plane.wait_idle(timeout=180)
            for h, seed in zip(healthy, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            # The sick tenant RECOVERED: completed, bit-identical, with
            # the restart documented in its own labelled telemetry.
            assert_healthy_matches_oracle(sick, solo_oracle, 999)
            counters = sick.report.snapshot["counters"]
            assert counters["supervisor.restarts"] == 1
            assert counters["sdc.mismatches"] == 1
            assert plane.health()["supervisor_restarts"] == 1

    def test_hang_is_bounded_and_isolated(self, tmp_path, solo_oracle):
        """A wedged dispatch pins ONE worker for exactly the deadline:
        the sick tenant aborts via its own watchdog, healthy tenants and
        the pod's health surface are untouched."""
        sick_params = tenant_params(tmp_path / "sick", 999)
        sick_backend = FaultInjectionBackend(
            Backend(sick_params),
            FaultPlan([Fault(1, "hang", seconds=90.0)]),
        )
        t0 = time.monotonic()
        try:
            with ServePlane(
                ServeConfig(max_sessions=3, default_deadline_seconds=1.0),
                checkpoint_root=tmp_path / "ckpt",
            ) as plane:
                healthy = submit_healthy(plane, tmp_path)
                sick = plane.submit("sick", sick_params, backend=sick_backend)
                assert plane.wait_idle(timeout=120)
                elapsed = time.monotonic() - t0
                assert elapsed < 45, f"watchdog abort took {elapsed:.1f}s"
                for h, seed in zip(healthy, HEALTHY_SEEDS):
                    assert_healthy_matches_oracle(h, solo_oracle, seed)
                assert sick.status == "parked" and sick.resumable
                assert "DispatchTimeout" in sick.error
                hl = plane.health()
                assert hl["watchdog_fires"] >= 1
                assert hl["live"]
                assert_pod_survives(plane, tmp_path, solo_oracle)
        finally:
            sick_backend.release_hangs()

    def test_flood_is_shed_beside_healthy_tenants(self, tmp_path, solo_oracle):
        """The noisy-neighbour row: a max-rate flood fills the free slot
        and the bounded queue, the rest is shed deterministically, queue
        depth and memory stay bounded (obs gauges), and the healthy
        tenants never notice."""
        with ServePlane(
            ServeConfig(max_sessions=3, max_queued=2)
        ) as plane:
            # 2 of 3 slots, latency-paced (bit-identical; ~2 s residency)
            # so the deterministic ladder below cannot race a healthy
            # tenant COMPLETING — and freeing its slot — before the
            # flood's first submission lands (warm-jit rigs are fast
            # enough for that, and this suite's order is not a contract).
            healthy = submit_healthy(plane, tmp_path, pace_seconds=0.3)
            flood = FloodTenant(
                plane,
                lambda t: tenant_params(tmp_path / t, 7),
                FaultPlan([Fault(0, "flood", cells=6)]),
            )
            tally = flood.run()
            # Deterministic ladder: 1 free slot, 2 queue places, 3 shed.
            assert tally == {
                "submitted": 6, "admitted": 1, "queued": 2, "rejected": 3,
            }
            assert [v for _, v in flood.outcomes] == [
                "admitted", "queued", "queued",
                "rejected", "rejected", "rejected",
            ]
            # Bounded backpressure, visible to a balancer.
            snap = plane.metrics.snapshot().to_dict()
            assert snap["gauges"]["serve.queued_sessions"] <= 2
            assert snap["gauges"]["serve.resident_sessions"] <= 3
            hl = plane.health()
            assert hl["rejected"] == 3
            assert all(e.retry_after is not None for e in flood.rejections)
            assert plane.wait_idle(timeout=300)
            for h, seed in zip(healthy, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            # Admitted flood sessions ran to completion too — a flood is
            # real load, not a mocked counter bump.
            for h in flood.handles:
                assert h.wait(timeout=120) and h.status == "completed"

    def test_flood_plan_is_rejected_at_the_dispatch_seam(self, tmp_path):
        """Handing a flood-bearing plan to the dispatch-seam harness is a
        test-harness bug, caught at construction."""
        params = tenant_params(tmp_path, 1)
        with pytest.raises(ValueError, match="admission seam"):
            FaultInjectionBackend(
                Backend(params), FaultPlan([Fault(0, "flood")])
            )


# -- graceful pod drain (tentpole leg 3) ---------------------------------------


@pytest.mark.chaos
class TestPodDrain:
    def test_sigterm_drains_all_residents_resumable(
        self, tmp_path, solo_oracle
    ):
        """The acceptance row: a REAL SIGTERM against a pod with N
        resident sessions emergency-checkpoints all N (fsync-durable via
        the PR-5 ``_checkpoint_now`` path), the pod exits cleanly, and a
        fresh pod re-adopts each tenant to the oracle state."""
        seeds = {"a": 11, "b": 22, "c": 33}
        root = tmp_path / "ckpt"
        plane = ServePlane(ServeConfig(max_sessions=3), checkpoint_root=root)
        restore = plane.install(signals=(signal.SIGTERM,))
        try:
            handles = {
                t: plane.submit(
                    t, tenant_params(tmp_path / t, seed, turns=10**6)
                )
                for t, seed in seeds.items()
            }
            # Let every tenant make real progress first.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                h.last_turn >= SUPERSTEP for h in handles.values()
            ):
                time.sleep(0.05)
            os.kill(os.getpid(), signal.SIGTERM)
            # The handler is non-blocking; the pod empties as each
            # session parks.  time.sleep keeps the main thread
            # signal-responsive.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                h.done for h in handles.values()
            ):
                time.sleep(0.05)
        finally:
            restore()
        for t, h in handles.items():
            assert h.status == "drained", (t, h.status, h.error)
            assert h.resumable
            assert 0 < h.last_turn < 10**6
        summary = plane.drain()  # already drained: returns the receipt
        assert {t: s["resumable"] for t, s in summary.items()} == {
            t: True for t in seeds
        }
        plane.close()

        # -- the restarted pod --
        plane2 = ServePlane(ServeConfig(max_sessions=3), checkpoint_root=root)
        adoptable = plane2.resumable_tenants()
        assert set(adoptable) == set(seeds)
        for t, info in adoptable.items():
            assert info["turn"] == handles[t].last_turn
            assert info["shape"] == [H, W]
        # Re-adopt toward a turn target PAST the park point so the
        # resumed run has work left (a fixed TURNS target could land
        # under a park turn and be flaky).
        resumed = {}
        for t, seed in seeds.items():
            target = adoptable[t]["turn"] + 2 * SUPERSTEP
            resumed[t] = plane2.submit(
                t,
                tenant_params(tmp_path / f"resumed-{t}", seed, turns=target),
            )
        assert plane2.wait_idle(timeout=180)
        for t, h in resumed.items():
            assert h.status == "completed", (t, h.status, h.error)
            assert h.last_turn == adoptable[t]["turn"] + 2 * SUPERSTEP
        plane2.close()

        # Oracle equality: an uninterrupted solo run to the same turn
        # target must produce the identical final board.
        for t, seed in seeds.items():
            target = adoptable[t]["turn"] + 2 * SUPERSTEP
            solo_out = tmp_path / f"oracle-{t}"
            p = tenant_params(solo_out, seed, turns=target)
            events: queue.Queue = queue.Queue()
            gol.run(p, events)
            while events.get(timeout=60) is not None:
                pass
            want = (solo_out / f"{p.final_output_name}.pgm").read_bytes()
            got = (
                tmp_path / f"resumed-{t}" / f"{W}x{H}x{target}.pgm"
            ).read_bytes()
            assert got == want, f"re-adopted tenant {t} diverged from oracle"

    def test_drain_sheds_the_waiting_queue(self, tmp_path):
        """Queued admissions never ran: a drain must terminate their
        streams explicitly (status 'shed'), not leave consumers hanging."""
        with ServePlane(ServeConfig(max_sessions=1, max_queued=2)) as plane:
            running = plane.submit(
                "run", tenant_params(tmp_path / "run", 1, turns=10**6)
            )
            queued = [
                plane.submit(f"q{i}", tenant_params(tmp_path / f"q{i}", i))
                for i in range(2)
            ]
            plane.begin_drain()
            for h in queued:
                assert h.wait(timeout=30)
                assert h.status == "shed"
                assert not h.resumable
                # The stream is terminated for any waiting consumer.
                assert h.events.get(timeout=10) is None
            assert running.wait(timeout=60)
            assert running.status == "drained"

    def test_drain_is_idempotent_and_admissions_stay_closed(self, tmp_path):
        with ServePlane(ServeConfig()) as plane:
            plane.begin_drain()
            plane.begin_drain()  # no double shed / double count
            with pytest.raises(AdmissionRejected, match="draining"):
                plane.submit("late", tenant_params(tmp_path, 1))
            hl = plane.health()
            assert hl["draining"] and not hl["ready"] and hl["live"]


# -- flight-report rendering (satellite) ---------------------------------------


class TestFlightReportRendering:
    def test_pr5_kinds_render_dedicated_rows(self, tmp_path):
        """Pinning test on a SUPERVISOR-PRODUCED flight record: drive a
        restart-exhaustion abort (restarts + exhaustion in the ring),
        then assert the report renders the resilience kinds as prose
        rows, not generic key=value fallthrough."""
        from distributed_gol_tpu.engine.supervisor import supervise
        from tools import flight_report

        params = tenant_params(
            tmp_path / "out", 1,
            checkpoint_every_turns=SUPERSTEP, restart_limit=2,
        )
        (tmp_path / "out").mkdir()
        plan = FaultPlan([Fault(0, "issue"), Fault(1, "issue")])

        def always_faulty(p, attempt):
            return FaultInjectionBackend(Backend(p), plan)

        events: queue.Queue = queue.Queue()
        with pytest.raises(RuntimeError):
            supervise(params, events, backend_factory=always_faulty)
        while events.get(timeout=60) is not None:
            pass

        from distributed_gol_tpu.obs import flight as flight_lib

        path = flight_lib.latest_flight_record(tmp_path / "out")
        assert path is not None
        doc = flight_lib.load_flight_record(path)
        text = flight_report.render(doc, tail=100)
        # The dedicated rows (no raw attempt=1 key=value fallthrough).
        assert "supervisor restart #1 after RuntimeError" in text
        assert "supervisor restart #2 after RuntimeError" in text
        assert "supervisor EXHAUSTED after 2 restart(s)" in text
        # No raw key=value fallthrough for the dedicated kinds
        # (terminal_failure rows legitimately stay generic).
        assert "from_turn=" not in text
        assert "resume_turn=" not in text
        assert "restarts=" not in text

    def test_all_resilience_kinds_have_renderers(self):
        """Synthetic ring covering every PR-5 kind: each renders its
        dedicated prose (generic fallthrough would print 'turn=7'), and
        unknown kinds still fall through so nothing is ever dropped."""
        from tools.flight_report import render

        records = [
            {"kind": "restart", "t": 1.0, "attempt": 1, "cause": "DispatchTimeout",
             "from_turn": 12, "resume_turn": 8, "tier": "same"},
            {"kind": "supervisor_exhausted", "t": 2.0, "restarts": 2,
             "cause": "RuntimeError"},
            {"kind": "sdc_check", "t": 3.0, "turn": 7, "ok": True,
             "fingerprint": 123, "stripe": True},
            {"kind": "sdc_mismatch", "t": 4.0, "turn": 7, "stripe_ok": False,
             "popcount": 10, "count": 11},
            {"kind": "preempt", "t": 5.0, "turn": 9},
            {"kind": "ckpt_skipped_unverified", "t": 6.0, "turn": 9},
            {"kind": "preempt_save_skipped", "t": 7.0, "turn": 9},
            # The ISSUE 7 elastic-recovery kinds.
            {"kind": "device_blacklist", "t": 7.2, "attempt": 3, "probed": 8,
             "condemned": [7], "blacklist": [7]},
            {"kind": "mesh_shrink", "t": 7.4, "attempt": 3,
             "from_shape": [8, 1], "to_shape": [2, 2], "healthy": 7},
            {"kind": "restart", "t": 7.5, "attempt": 3, "cause": "RuntimeError",
             "from_turn": 20, "resume_turn": 15, "tier": "elastic",
             "mesh_shape": [2, 2], "excluded_devices": [7]},
            {"kind": "elastic_exhausted", "t": 7.6, "attempt": 4,
             "error": "all condemned"},
            {"kind": "peer_lost", "t": 7.8, "ranks": [1], "timeout_s": 1.5},
            {"kind": "some_future_kind", "t": 8.0, "detail": 42},
            {"kind": "abort", "t": 9.0, "cause": "RuntimeError"},
        ]
        doc = {
            "schema": "gol-flight-v1", "cause": "RuntimeError", "turn": 9,
            "error": "boom", "written_at": 9.0, "records": records,
            "metrics": {},
        }
        text = render(doc, tail=100)
        assert "rolled back turn 12 -> 8" in text
        assert "supervisor EXHAUSTED after 2 restart(s)" in text
        assert "SDC check at turn 7: ok (stripe+fingerprint, fp=123)" in text
        assert "SDC MISMATCH at turn 7: popcount 10 vs forced count 11" in text
        assert "graceful stop latched at turn 9" in text
        assert "checkpoint WITHHELD at turn 9" in text
        assert "emergency save WITHHELD at turn 9" in text
        assert "elastic probe (attempt 3): 8 device(s) probed" in text
        assert "condemned device(s) [7]; blacklist now [7]" in text
        assert "mesh SHRUNK 8x1 -> 2x2 on 7 healthy device(s)" in text
        assert "(elastic tier on mesh 2x2, devices [7] excluded)" in text
        assert "elastic rung EXHAUSTED (attempt 4)" in text
        assert "peer rank(s) [1] LOST" in text
        assert "1.5s heartbeat bound" in text
        assert "detail=42" in text  # unknown kind: generic row, not dropped


# -- the serve CLI subcommand --------------------------------------------------


class TestServeCli:
    def test_serve_subcommand_end_to_end(self, tmp_path, capsys):
        from distributed_gol_tpu.__main__ import serve_main

        rc = serve_main(
            [
                "--tenant", "alice:16x16x24",
                "--tenant", "bob:16x16x12",
                "--checkpoint-root", str(tmp_path / "ckpt"),
                "--superstep", "4",
                "--engine", "roll",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["sessions"]["alice"]["status"] == "completed"
        assert doc["sessions"]["alice"]["turn"] == 24
        assert doc["sessions"]["bob"]["turn"] == 12
        assert doc["health"]["live"]
        assert doc["health"]["tenants"]["alice"]["turns"] == 24

    def test_tenant_spec_parse_errors(self):
        from distributed_gol_tpu.__main__ import _parse_tenant_spec

        assert _parse_tenant_spec("a:16x32x100") == ("a", 16, 32, 100)
        # An empty name is a usage error AT PARSE TIME (ap.error), not a
        # raw Params traceback from inside submit.
        for bad in ("a", "a:16x32", "a:16x32xfoo", ":16x16x1"):
            with pytest.raises(ValueError, match="NAME:WxHxTURNS"):
                _parse_tenant_spec(bad)


# -- batched dispatch cohorts (ISSUE 8 tentpole) --------------------------------
#
# N resident same-key sessions share ONE device launch per superstep
# (serve/batcher.py).  Contracts pinned here: bit-identity of every
# cohort-served tenant to its solo oracle, launch economics (one batched
# launch per superstep, however many tenants), cohort-key separation for
# any dispatch-relevant Params difference, per-tenant obs labels
# surviving shared launches, and the chaos rows — a faulted or straggling
# slot is evicted back to a solo launch while its healthy cohort-mates
# stay bit-identical and batched.


class TestCohortKey:
    def test_identity_fields_do_not_split(self, tmp_path):
        from distributed_gol_tpu.serve import cohort_key

        a = tenant_params(tmp_path / "a", 1, tenant="alice")
        b = tenant_params(tmp_path / "b", 2, tenant="bob")
        assert cohort_key(a) == cohort_key(b)

    @pytest.mark.parametrize(
        "override",
        [
            {"sdc_check_every_turns": SUPERSTEP},
            {"rule": "highlife"},
            {"superstep": SUPERSTEP * 2},
            {"turns": TURNS * 2},
            {"engine": "packed"},
            {"image_width": 32},
            # Time compression (ISSUE 16) changes the dispatch schedule
            # (probe deferral + zero-launch fast-forward), so a
            # compressed and a dense tenant must never share a launch.
            {"time_compression": True},
            {"timecomp_cache_slots": 8},
        ],
        ids=lambda o: next(iter(o)),
    )
    def test_dispatch_relevant_fields_split(self, tmp_path, override):
        from distributed_gol_tpu.models.life import RULES
        from distributed_gol_tpu.serve import cohort_key

        if "rule" in override:
            override = {"rule": RULES["highlife"]}
        a = tenant_params(tmp_path, 1)
        b = tenant_params(tmp_path, 1, **override)
        assert cohort_key(a) != cohort_key(b)


class TestBatchedCohorts:
    def _plane(self, n=3, **kw):
        return ServePlane(ServeConfig(max_sessions=n, batched=True, **kw))

    def test_cohort_completes_bit_identical_one_launch_per_superstep(
        self, tmp_path, solo_oracle
    ):
        """The headline contract: three tenants, six supersteps, six
        batched launches carrying three boards each — and every tenant's
        final board is byte-identical to its fault-free solo oracle."""
        for seed in HEALTHY_SEEDS + (303,):
            solo_oracle(seed)  # outside the launch-accounting window
        before = obs_metrics.REGISTRY.snapshot()
        with self._plane() as plane:
            handles = [
                plane.submit(f"t{s}", tenant_params(tmp_path / f"t{s}", s))
                for s in HEALTHY_SEEDS + (303,)
            ]
            assert plane.wait_idle(timeout=120)
            for h, seed in zip(handles, HEALTHY_SEEDS + (303,)):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            hl = plane.health()
            assert hl["batched"]
        counters = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        supersteps = TURNS // SUPERSTEP
        # Every one of the 3x6 member dispatches rode a batched launch
        # (none fell back solo), and the physical launch count is one
        # per superstep — at most one extra for a split start-up round,
        # where a member dispatched before the rest had registered.
        assert counters.get("serve.batched_boards") == 3 * supersteps
        assert supersteps <= counters.get("serve.batched_launches") <= supersteps + 1
        assert not counters.get("serve.cohort_evictions")
        solo_launches = sum(
            v for k, v in counters.items() if k.startswith("backend.dispatches.")
        )
        assert solo_launches == 0

    def test_mismatched_params_do_not_share_a_cohort(self, tmp_path, solo_oracle):
        """Satellite 3: same shape, different ``sdc_check_every_turns``
        — the cohort key must split them (a silently shared launch would
        desync the sentinel's dispatch schedule), and both still
        complete to their oracles.  The proof is behavioural: every
        fired round carried exactly ONE board (launches == boards), so
        the two tenants never shared a launch."""
        for seed in (101, 202):
            solo_oracle(seed)
        before = obs_metrics.REGISTRY.snapshot()
        with self._plane() as plane:
            plain = plane.submit(
                "plain", tenant_params(tmp_path / "plain", 101)
            )
            sentinel = plane.submit(
                "sentinel",
                tenant_params(
                    tmp_path / "sentinel", 202,
                    sdc_check_every_turns=SUPERSTEP,
                ),
            )
            assert plane.wait_idle(timeout=120)
            assert_healthy_matches_oracle(plain, solo_oracle, 101)
            assert_healthy_matches_oracle(sentinel, solo_oracle, 202)
        counters = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        launches = counters.get("serve.batched_launches", 0)
        assert launches >= 2 * (TURNS // SUPERSTEP)
        assert counters.get("serve.batched_boards") == launches

    def test_per_tenant_labels_survive_cohort_launches(
        self, tmp_path, solo_oracle
    ):
        """Satellite 2 pinned test: a cohort run's labelled snapshot
        equals a solo run's — one batched dispatch still bumps each
        tenant's own ``controller.dispatches``/``controller.turns``
        (``DispatchRecorder`` is per-session), so ``health()`` per-tenant
        counts stay truthful under shared launches."""
        with self._plane() as plane:
            handles = [
                plane.submit(f"t{s}", tenant_params(tmp_path / f"t{s}", s))
                for s in HEALTHY_SEEDS
            ]
            assert plane.wait_idle(timeout=120)
            hl = plane.health()
        for h, seed in zip(handles, HEALTHY_SEEDS):
            assert_healthy_matches_oracle(h, solo_oracle, seed)
            counters = h.report.snapshot["counters"]
            t = h.tenant
            # Identical to the solo-run values TestTenantLabels pins: the
            # shared launch splits into per-tenant logical dispatches.
            assert counters[f"controller.turns{{tenant={t}}}"] == TURNS
            assert (
                counters[f"controller.dispatches{{tenant={t}}}"]
                == TURNS // SUPERSTEP
            )
            assert hl["tenants"][t]["turns"] == TURNS
            assert hl["tenants"][t]["dispatches"] == TURNS // SUPERSTEP

    def test_failed_batched_launch_demotes_round_to_solo(
        self, tmp_path, solo_oracle, monkeypatch
    ):
        """A batched launch that FAILS (build/trace error at that arity)
        demotes its whole round to permanent solo launches: one doomed
        attempt, never one per superstep — and every session still
        completes bit-identical on the inherited solo path."""
        from distributed_gol_tpu.engine.backend import BatchedBackend

        def boom(self, boards, turns):
            raise RuntimeError("forced batched-launch failure")

        monkeypatch.setattr(BatchedBackend, "run_boards", boom)
        for seed in HEALTHY_SEEDS:
            solo_oracle(seed)
        before = obs_metrics.REGISTRY.snapshot()
        with self._plane(n=2) as plane:
            handles = [
                plane.submit(f"t{s}", tenant_params(tmp_path / f"t{s}", s))
                for s in HEALTHY_SEEDS
            ]
            assert plane.wait_idle(timeout=120)
            for h, seed in zip(handles, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
        counters = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        # <= 2 failed attempts (one per start-up round at worst), not one
        # per superstep; all real work ran as solo dispatches.
        assert 1 <= counters.get("serve.batched_launch_failures", 0) <= 2
        assert not counters.get("serve.batched_launches")
        assert sum(
            v for k, v in counters.items()
            if k.startswith("backend.dispatches.")
        ) == 2 * (TURNS // SUPERSTEP)

    def test_cohort_membership_follows_retirement(self, tmp_path, solo_oracle):
        """A shorter run leaving the pod leaves its cohort (retire), so
        later rounds stop waiting for it — the remaining tenants keep
        batching to completion."""
        for seed in HEALTHY_SEEDS:
            solo_oracle(seed)
        before = obs_metrics.REGISTRY.snapshot()
        with self._plane() as plane:
            short = plane.submit(
                "short",
                tenant_params(tmp_path / "short", 7, turns=SUPERSTEP),
            )
            long_h = [
                plane.submit(f"t{s}", tenant_params(tmp_path / f"t{s}", s))
                for s in HEALTHY_SEEDS
            ]
            assert plane.wait_idle(timeout=120)
            assert short.status == "completed"
            for h, seed in zip(long_h, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            assert plane.batcher.cohort_of("short") is None
        counters = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        # The survivors' rounds after the short tenant left still batch
        # (2 boards/round), so boards > launches.
        assert counters["serve.batched_launches"] >= TURNS // SUPERSTEP
        assert counters["serve.batched_boards"] > counters["serve.batched_launches"]


@pytest.mark.chaos
class TestCohortChaos:
    def test_burst_faulted_slot_inside_a_cohort(self, tmp_path, solo_oracle):
        """THE acceptance chaos row: a burst-faulted tenant INSIDE a
        cohort parks alone (PR-2 retry budget), the two healthy
        cohort-mates stay bit-identical to their solo oracles and keep
        batching, and the pod survives."""
        with ServePlane(
            ServeConfig(
                max_sessions=3,
                batched=True,
                cohort_grace_seconds=0.1,
            ),
            checkpoint_root=tmp_path / "ckpt",
        ) as plane:
            healthy = [
                plane.submit(f"good{i}", tenant_params(tmp_path / f"good{i}", s))
                for i, s in enumerate(HEALTHY_SEEDS)
            ]
            # Tenant stamped HERE (the plane normally stamps it at
            # submit): member_backend cohorts by tenant identity.
            sick_params = tenant_params(tmp_path / "sick", 999, tenant="sick")
            # The fault harness wraps the COHORT MEMBER backend at the
            # dispatch seam — exactly how it wraps a solo Backend — so
            # the injected failures strike before the rendezvous and the
            # sick tenant simply stops showing up for its cohort.
            sick_member = plane.batcher.member_backend(sick_params)
            assert sick_member.__class__.__name__ == "_CohortMember"
            sick_backend = FaultInjectionBackend(
                sick_member,
                FaultPlan([Fault(2, "issue"), Fault(3, "issue")]),
            )
            sick = plane.submit("sick", sick_params, backend=sick_backend)
            assert plane.wait_idle(timeout=180)
            for h, seed in zip(healthy, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            assert sick.status == "parked" and sick.resumable
            assert "RuntimeError" in sick.error
            assert plane.health()["live"]
            # The pod still admits and completes fresh (batched) work.
            after = plane.submit(
                "after", tenant_params(tmp_path / "after", 303)
            )
            assert after.wait(timeout=120)
            assert_healthy_matches_oracle(after, solo_oracle, 303)
        # Parked-resumable means exactly that, cohort or not.
        events: queue.Queue = queue.Queue()
        gol.run(
            tenant_params(tmp_path / "resumed", 999),
            events,
            session=Session(tmp_path / "ckpt" / "sick"),
        )
        while events.get(timeout=60) is not None:
            pass
        got = tmp_path / "resumed" / f"{W}x{H}x{TURNS}.pgm"
        assert got.read_bytes() == solo_oracle(999)

    def test_straggler_evicted_to_solo_launches(self, tmp_path, solo_oracle):
        """The eviction ladder end-to-end: a latency-faulted slot misses
        its cohort's rounds (grace-bounded), is evicted after the miss
        budget, finishes SOLO bit-identical to its oracle — and the
        healthy mates never slow below the grace bound per round."""
        for seed in HEALTHY_SEEDS + (999,):
            solo_oracle(seed)
        before = obs_metrics.REGISTRY.snapshot()
        with ServePlane(
            ServeConfig(
                max_sessions=3,
                batched=True,
                cohort_grace_seconds=0.05,
                cohort_evict_misses=2,
            )
        ) as plane:
            slow_params = tenant_params(tmp_path / "slow", 999, tenant="slow")
            member = plane.batcher.member_backend(slow_params)
            assert member.__class__.__name__ == "_CohortMember"
            slow_backend = FaultInjectionBackend(
                member,
                FaultPlan(
                    [Fault(k, "latency", seconds=0.6) for k in range(2, 5)]
                ),
            )
            healthy = [
                plane.submit(f"good{i}", tenant_params(tmp_path / f"good{i}", s))
                for i, s in enumerate(HEALTHY_SEEDS)
            ]
            slow = plane.submit("slow", slow_params, backend=slow_backend)
            assert plane.wait_idle(timeout=180)
            for h, seed in zip(healthy, HEALTHY_SEEDS):
                assert_healthy_matches_oracle(h, solo_oracle, seed)
            # The straggler was evicted back to solo launches — and its
            # run is still bit-identical (eviction is a performance
            # decision, never a correctness one).
            assert_healthy_matches_oracle(slow, solo_oracle, 999)
            assert member.solo, "straggler should have been evicted"
        counters = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        assert counters.get("serve.cohort_evictions", 0) >= 1
        # Evicted solo launches are visible as ordinary backend dispatches.
        assert any(
            k.startswith("backend.dispatches.") for k in counters
        )
