"""Megakernel compile-cache discipline + platform-table loudness.

Round-6 satellites: the frontier megakernel's launch count used to be a
raw ``lru_cache`` key, so the controller's doubling dispatch calibration
compiled a fresh ~10 s Mosaic kernel per depth and the cache grew without
bound; dispatches now decompose into canonical chunk lengths
(``_NLAUNCH_CANON``).  And a TPU generation missing from the VMEM table
must say so once instead of silently running the v5e-tuned plan.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops import packed, pallas_packed as pp


class TestNlaunchChunks:
    def test_exact_cover_and_canonical_membership(self):
        for full in list(range(0, 70)) + [127, 512, 513, 2900, 10_000]:
            chunks, loose = pp._nlaunch_chunks(full)
            assert sum(chunks) + loose == full
            assert set(chunks) <= set(pp._NLAUNCH_CANON)
            assert 0 <= loose < min(pp._NLAUNCH_CANON)

    def test_doubling_sequence_bounded_compiles(self):
        # The controller's calibration shape: dispatch depth doubling from
        # 1 launch to 4096.  However far it grows, the megakernel compile
        # set stays within the canonical sizes (<= 3 distinct).
        seen = set()
        for k in range(13):  # 1, 2, 4, ..., 4096
            chunks, loose = pp._nlaunch_chunks(1 << k)
            seen.update(chunks)
        assert len(seen) <= 3
        assert seen <= set(pp._NLAUNCH_CANON)

    def test_chunks_are_even(self):
        # Even chunk lengths keep each chunk's final board in output a —
        # the buffer-threading invariant the dispatch loops lean on.
        assert all(c % 2 == 0 for c in pp._NLAUNCH_CANON)

    @pytest.mark.slow
    def test_dispatch_ladder_compiles_at_most_three_megakernels(self):
        """An adaptive/doubling dispatch sequence (the calibration ladder)
        hits ≤ 3 distinct megakernel compiles — measured at the cache, on
        real dispatches of the single-device engine, with bit-identity
        against the XLA packed engine as the side oracle."""
        shape = (512, 128)  # (H, wp): hosts a frontier plan at T=18
        t, adaptive = pp.adaptive_launch_depth(shape, 10**6, 1024)
        assert adaptive and pp._frontier_plan(shape, t, 1024) is not None
        rng = np.random.default_rng(5)
        board = np.zeros((512, 4096), dtype=np.uint8)
        board[40:44, 100:140] = np.where(
            rng.random((4, 40)) < 0.5, 255, 0
        )
        p = packed.pack(jnp.asarray(board))
        run = pp.make_superstep(CONWAY, skip_stable=True)
        before = pp._build_dispatch_frontier.cache_info()
        state = np.asarray(p)
        total = 0
        for k in range(7):  # full = 1, 2, 4, ..., 64 launches
            turns = t * (1 << k)
            state = np.asarray(run(jnp.asarray(state), turns))
            total += turns
        after = pp._build_dispatch_frontier.cache_info()
        assert after.misses - before.misses <= 3
        ref = np.asarray(packed.superstep(p, CONWAY, total))
        assert np.array_equal(state, ref)


class TestUnknownDeviceKindWarning:
    def _fake_tpu(self, monkeypatch, kind):
        class Dev:
            device_kind = kind

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(jax, "devices", lambda: [Dev()])

    def test_unknown_kind_warns_once_and_uses_baseline(self, monkeypatch):
        self._fake_tpu(monkeypatch, "TPU v9 hypothetical")
        pp._vmem_physical.cache_clear()
        try:
            with pytest.warns(RuntimeWarning, match="BASELINE.md"):
                assert pp._vmem_physical() == pp._VMEM_BASELINE
            # lru_cache makes the warning once-per-process: a second call
            # never re-enters the body.
            import warnings as _w

            with _w.catch_warnings():
                _w.simplefilter("error")
                assert pp._vmem_physical() == pp._VMEM_BASELINE
        finally:
            pp._vmem_physical.cache_clear()

    def test_known_kind_stays_silent(self, monkeypatch):
        self._fake_tpu(monkeypatch, "TPU v5 lite")
        pp._vmem_physical.cache_clear()
        try:
            import warnings as _w

            with _w.catch_warnings():
                _w.simplefilter("error")
                assert pp._vmem_physical() == pp._VMEM_BY_KIND["TPU v5 lite"]
        finally:
            pp._vmem_physical.cache_clear()
