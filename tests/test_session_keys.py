"""Keypress semantics + pause/detach/resume checkpointing.

Behavioural spec: gol/distributor.go:105-151 (keypress manager),
broker/broker.go:124-155 (pause/CheckStates contract).  The reference never
tests these paths in isolation (SURVEY.md §4: no unit tests); these are the
added hermetic coverage.
"""

import queue
import threading
import time

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.pgm import read_pgm
from distributed_gol_tpu.engine.session import Checkpoint, Session


def make_params(tmp_path, input_images, **kw):
    defaults = dict(
        turns=10**6,
        image_width=16,
        image_height=16,
        images_dir=input_images,
        out_dir=tmp_path,
        ticker_period=0.2,
        superstep=5,
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def start_run(params, session):
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    thread = gol.start(params, events, keys, session)
    return events, keys, thread


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


def wait_for_turns(events, min_turn, collected, timeout=30):
    """Consume events until a TurnComplete >= min_turn is seen."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            e = events.get(timeout=0.5)
        except queue.Empty:
            continue
        if e is None:
            raise AssertionError("stream ended early")
        collected.append(e)
        if isinstance(e, gol.TurnComplete) and e.completed_turns >= min_turn:
            return
    raise AssertionError(f"no TurnComplete >= {min_turn} within {timeout}s")


class TestPause:
    def test_pause_stops_stepping_and_resume_continues(
        self, tmp_path, input_images
    ):
        session = Session()
        events, keys, thread = start_run(
            make_params(tmp_path, input_images), session
        )
        seen = []
        wait_for_turns(events, 10, seen)
        keys.put("p")
        # Find the StateChange{Paused}; note the turn at which it paused.
        deadline = time.monotonic() + 10
        paused_evt = None
        while paused_evt is None and time.monotonic() < deadline:
            e = events.get(timeout=5)
            assert e is not None
            seen.append(e)
            if isinstance(e, gol.StateChange) and e.new_state is gol.State.PAUSED:
                paused_evt = e
        assert paused_evt is not None
        assert session.paused
        # While paused, no new TurnComplete events appear...
        time.sleep(0.6)
        frozen = [
            e
            for e in _drain_nonblocking(events)
            if isinstance(e, gol.TurnComplete)
        ]
        max_frozen = max(
            [e.completed_turns for e in frozen], default=paused_evt.completed_turns
        )
        time.sleep(0.6)
        later = _drain_nonblocking(events)
        assert not any(isinstance(e, gol.TurnComplete) for e in later)
        # ...but the ticker still ticks (reference: ticker runs during pause).
        time.sleep(0.5)
        assert any(
            isinstance(e, gol.AliveCellsCount) for e in _drain_nonblocking(events)
        )
        keys.put("p")  # resume
        more = []
        wait_for_turns(events, max_frozen + 1, more)
        assert any(
            isinstance(e, gol.StateChange) and e.new_state is gol.State.EXECUTING
            for e in more
        )
        keys.put("k")
        thread.join(timeout=30)
        assert not thread.is_alive()


class TestSnapshot:
    def test_s_writes_current_board(self, tmp_path, input_images, golden_images):
        """'s' at a known turn: snapshot must equal the golden board for that
        turn (we pause first so the turn is deterministic)."""
        session = Session()
        params = make_params(tmp_path, input_images, superstep=1, turns=100)
        events, keys, thread = start_run(params, session)
        seen = []
        wait_for_turns(events, 1, seen)
        keys.put("p")
        time.sleep(0.5)
        keys.put("s")
        keys.put("p")
        thread.join(timeout=60)
        imgs = [e for e in drain(events) if isinstance(e, gol.ImageOutputComplete)]
        assert imgs, "no ImageOutputComplete after 's'"
        snap_turn = imgs[0].completed_turns
        snap = read_pgm(tmp_path / f"{imgs[0].filename}.pgm")
        assert imgs[0].filename == f"16x16x{snap_turn}current"
        if snap_turn in (0, 1, 100):
            golden = read_pgm(golden_images / f"16x16x{snap_turn}.pgm")
            np.testing.assert_array_equal(snap, golden)


class TestDetachResume:
    def test_q_then_resume_in_memory(self, tmp_path, input_images):
        session = Session()
        events, keys, thread = start_run(
            make_params(tmp_path, input_images), session
        )
        seen = []
        wait_for_turns(events, 20, seen)
        keys.put("q")
        thread.join(timeout=30)
        all_events = seen + drain(events)
        final = [e for e in all_events if isinstance(e, gol.FinalTurnComplete)][0]
        detach_turn = final.completed_turns
        assert final.alive == ()  # detach carries no board (quirk Q2 semantics)
        assert any(
            isinstance(e, gol.StateChange) and e.new_state is gol.State.QUITTING
            for e in all_events
        )
        # New controller with the same session: resumes at detach_turn + 1.
        params2 = make_params(
            tmp_path, input_images, turns=detach_turn + 10, superstep=1
        )
        events2: queue.Queue = queue.Queue()
        gol.run(params2, events2, None, session)
        log2 = drain(events2)
        first_tc = [e for e in log2 if isinstance(e, gol.TurnComplete)][0]
        assert first_tc.completed_turns == detach_turn + 1
        final2 = [e for e in log2 if isinstance(e, gol.FinalTurnComplete)][0]
        assert final2.completed_turns == detach_turn + 10

    def test_resume_requires_matching_size(self, tmp_path, input_images):
        session = Session()
        session.pause(True, world=np.zeros((32, 32), np.uint8), turn=7)
        # 16x16 params: size mismatch -> fresh start from the input PGM
        # (broker/broker.go:131-135 SameSize=false path).
        params = make_params(tmp_path, input_images, turns=3, superstep=1)
        events: queue.Queue = queue.Queue()
        gol.run(params, events, None, session)
        log = drain(events)
        first_tc = [e for e in log if isinstance(e, gol.TurnComplete)][0]
        assert first_tc.completed_turns == 1  # started from turn 0

    def test_resume_requires_matching_rule(self, tmp_path, input_images):
        """A checkpoint records its rule notation (framework extension: the
        reference has exactly one rule); resuming under a different rule is
        a different simulation, so it starts fresh — and, like a size
        mismatch, leaves the checkpoint parked for a matching controller."""
        from distributed_gol_tpu.models.life import HIGHLIFE

        session = Session()
        session.pause(
            True, world=np.zeros((16, 16), np.uint8), turn=7, rule="B36/S23"
        )
        params = make_params(tmp_path, input_images, turns=3, superstep=1)
        events: queue.Queue = queue.Queue()
        gol.run(params, events, None, session)  # Conway controller
        log = drain(events)
        first_tc = [e for e in log if isinstance(e, gol.TurnComplete)][0]
        assert first_tc.completed_turns == 1  # fresh start from turn 0
        # The checkpoint is still claimable by a HighLife controller.
        ck = session.check_states(16, 16, HIGHLIFE.notation)
        assert ck is not None and ck.turn == 7
        # Unknown-rule checkpoints (pre-extension) match any controller.
        session.pause(True, world=np.zeros((16, 16), np.uint8), turn=4)
        assert session.check_states(16, 16, "B3/S23") is not None

    def test_durable_checkpoint_records_rule(self, tmp_path, input_images):
        a = Session(tmp_path / "ckpt")
        a.pause(
            True, world=np.zeros((16, 16), np.uint8), turn=9, rule="B36/S23"
        )
        b = Session(tmp_path / "ckpt")  # fresh process analog
        assert b.check_states(16, 16, "B3/S23") is None  # wrong rule
        c = Session(tmp_path / "ckpt")
        ck = c.check_states(16, 16, "B36/S23")
        assert ck is not None and ck.turn == 9 and ck.rule == "B36/S23"

    def test_resume_consumed_exactly_once(self, tmp_path, input_images):
        session = Session()
        session.pause(True, world=np.zeros((16, 16), np.uint8), turn=5)
        ck = session.check_states(16, 16)
        assert ck is not None and ck.turn == 5
        assert session.check_states(16, 16) is None  # paused flag cleared

    def test_durable_checkpoint_across_processes(self, tmp_path, input_images):
        """'q' with a checkpoint_dir: a brand-new Session (new process
        analog) resumes from disk; the checkpoint is consumed exactly once."""
        ckpt_dir = tmp_path / "ckpt"
        s1 = Session(ckpt_dir)
        events, keys, thread = start_run(
            make_params(tmp_path, input_images), s1
        )
        seen = []
        wait_for_turns(events, 10, seen)
        keys.put("q")
        thread.join(timeout=30)
        final = [
            e
            for e in seen + drain(events)
            if isinstance(e, gol.FinalTurnComplete)
        ][0]
        s2 = Session(ckpt_dir)  # "new process"
        ck = s2.check_states(16, 16)
        assert ck is not None and ck.turn == final.completed_turns
        s3 = Session(ckpt_dir)  # resumed already consumed the paused flag
        assert s3.check_states(16, 16) is None


class TestKill:
    def test_k_snapshots_and_shuts_down(self, tmp_path, input_images):
        session = Session()
        events, keys, thread = start_run(
            make_params(tmp_path, input_images), session
        )
        seen = []
        wait_for_turns(events, 5, seen)
        keys.put("k")
        thread.join(timeout=30)
        log = seen + drain(events)
        imgs = [e for e in log if isinstance(e, gol.ImageOutputComplete)]
        assert imgs and (tmp_path / f"{imgs[-1].filename}.pgm").exists()
        assert [e for e in log if isinstance(e, gol.FinalTurnComplete)]
        assert session.is_shutdown
        # After 'k' nothing can resume (broker + workers are gone).
        assert session.check_states(16, 16) is None


class TestDurableCheckpointRobustness:
    """ISSUE 2 satellites: atomic + checksummed persistence, keep-last-K
    rotation, and corrupt-checkpoint degradation.  Hermetic (seeded soups,
    no reference data)."""

    def _board(self, seed=3):
        rng = np.random.default_rng(seed)
        return np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)

    def test_interrupted_persist_is_detected_not_resumed(self, tmp_path):
        """The crash window `Session._persist` used to leave open: a new
        world written but the sidecar not yet updated (or vice versa).
        With world-before-meta ordering + the CRC32 sidecar, the stale
        meta/world mismatch is detected and degrades to 'no checkpoint'
        with a one-time warning — never a silent resume of torn state."""
        import warnings

        ckpt_dir = tmp_path / "ckpt"
        s1 = Session(ckpt_dir)
        s1.pause(True, world=self._board(1), turn=5, rule="B3/S23")
        # Simulate the crash: a NEW world hit the disk (atomic in itself)
        # but the process died before the sidecar commit.
        from distributed_gol_tpu.engine.pgm import write_pgm

        write_pgm(ckpt_dir / "checkpoint.pgm", self._board(2))

        s2 = Session(ckpt_dir)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            assert s2.check_states(16, 16) is None
            assert s2.check_states(16, 16) is None
        warned = [w for w in caught if issubclass(w.category, RuntimeWarning)]
        assert len(warned) == 1 and "CRC32" in str(warned[0].message)

    def test_rotation_keeps_last_k_and_consumes_once(self, tmp_path):
        ckpt_dir = tmp_path / "ckpt"
        s1 = Session(ckpt_dir)
        for turn in (4, 8, 12, 16, 20):
            s1.save_checkpoint(self._board(turn), turn, rule="B3/S23", keep=3)
        pairs = sorted(p.name for p in ckpt_dir.glob("checkpoint-*.json"))
        assert len(pairs) == 3 and pairs[-1].startswith("checkpoint-")
        assert not (ckpt_dir / "checkpoint-000000000004.json").exists()
        assert not (ckpt_dir / "checkpoint-000000000004.pgm").exists()

        # A fresh process adopts the newest pair...
        s2 = Session(ckpt_dir)
        ck = s2.check_states(16, 16, "B3/S23")
        assert ck is not None and ck.turn == 20
        assert np.array_equal(ck.world, self._board(20))
        # ...and the consume covers the WHOLE rotation: another fresh
        # process must not adopt an older pair of the same run.
        assert Session(ckpt_dir).check_states(16, 16, "B3/S23") is None

    def test_torn_newest_falls_back_to_previous_pair(self, tmp_path):
        ckpt_dir = tmp_path / "ckpt"
        s1 = Session(ckpt_dir)
        s1.save_checkpoint(self._board(8), 8, keep=3)
        s1.save_checkpoint(self._board(16), 16, keep=3)
        torn = ckpt_dir / "checkpoint-000000000016.pgm"
        torn.write_bytes(torn.read_bytes()[:20])  # crash mid-write artifact

        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ck = Session(ckpt_dir).check_states(16, 16)
        assert ck is not None and ck.turn == 8
        assert np.array_equal(ck.world, self._board(8))
        assert any(issubclass(w.category, RuntimeWarning) for w in caught)

    def test_completed_run_discards_periodic_checkpoints(self, tmp_path):
        """Periodic checkpoints are crash insurance, not detach state: a
        run that COMPLETES must leave nothing to resume (same as today's
        no-checkpoint contract for clean runs)."""
        ckpt_dir = tmp_path / "ckpt"
        session = Session(ckpt_dir)
        params = gol.Params(
            turns=20,
            image_width=16,
            image_height=16,
            soup_density=0.3,
            soup_seed=7,
            out_dir=tmp_path,
            superstep=5,
            engine="roll",
            cycle_check=0,
            checkpoint_every_turns=5,
        )
        events: queue.Queue = queue.Queue()
        gol.run(params, events, session=session)
        stream = drain(events)
        saves = [e for e in stream if isinstance(e, gol.CheckpointSaved)]
        # One per due dispatch boundary, minus the final turn (the run
        # ended there; the final PGM is the durable artifact).
        assert [e.completed_turns for e in saves] == [5, 10, 15]
        assert Session(ckpt_dir).check_states(16, 16) is None
        assert not list(ckpt_dir.glob("checkpoint*"))

    def test_discard_leaves_foreign_detach_checkpoint_parked(self, tmp_path):
        """A completed run's discard must only remove ITS rotated pairs:
        a 'q'-detach checkpoint of a different board size sharing the
        directory stays claimable (the check_states mismatch contract)."""
        ckpt_dir = tmp_path / "ckpt"
        other = Session(ckpt_dir)  # run A: 32x32 detach, still parked
        other.pause(True, world=np.zeros((32, 32), np.uint8), turn=7)

        session = Session(ckpt_dir)
        params = gol.Params(
            turns=20,
            image_width=16,
            image_height=16,
            soup_density=0.3,
            soup_seed=7,
            out_dir=tmp_path,
            superstep=5,
            engine="roll",
            cycle_check=0,
            checkpoint_every_turns=5,
        )
        events: queue.Queue = queue.Queue()
        gol.run(params, events, session=session)  # 16x16: refuses A's pair
        drain(events)
        assert not list(ckpt_dir.glob("checkpoint-*")), "rotated pairs kept"
        ck = Session(ckpt_dir).check_states(32, 32)
        assert ck is not None and ck.turn == 7, "foreign detach pair lost"

    def test_failed_save_rolls_back_and_completed_run_stays_clean(
        self, tmp_path
    ):
        """An unwritable checkpoint dir: every periodic save fails — the
        run must warn once and keep computing, and its COMPLETION must not
        leave a stale resumable state (the failed save may not park the
        in-memory slot)."""
        import warnings

        blocker = tmp_path / "blocked"
        blocker.write_text("not a directory")  # mkdir() will raise
        session = Session(blocker)
        params = gol.Params(
            turns=20,
            image_width=16,
            image_height=16,
            soup_density=0.3,
            soup_seed=7,
            out_dir=tmp_path,
            superstep=5,
            engine="roll",
            cycle_check=0,
            checkpoint_every_turns=5,
        )
        events: queue.Queue = queue.Queue()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            gol.run(params, events, session=session)
        stream = drain(events)
        final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)]
        assert final and final[0].completed_turns == 20
        warned = [
            w
            for w in caught
            if issubclass(w.category, RuntimeWarning)
            and "periodic checkpoint" in str(w.message)
        ]
        assert len(warned) == 1, warned  # once per run, not per cadence
        assert not session.paused
        assert session.check_states(16, 16) is None

    def test_stale_consumed_record_does_not_shadow_newer_crash_pair(
        self, tmp_path
    ):
        """A consumed sidecar left by an earlier (resumed) run must not
        stop the scan: any pair still paused postdates that consume and
        is the newer run's crash state."""
        ckpt_dir = tmp_path / "ckpt"
        s1 = Session(ckpt_dir)  # run 1: detached at turn 50, then resumed
        s1.pause(True, world=self._board(50), turn=50)
        assert Session(ckpt_dir).check_states(16, 16) is not None  # consume
        s2 = Session(ckpt_dir)  # run 2: periodic pair at turn 10, "crash"
        s2.save_checkpoint(self._board(10), 10)

        ck = Session(ckpt_dir).check_states(16, 16)
        assert ck is not None and ck.turn == 10
        assert np.array_equal(ck.world, self._board(10))

    def test_crash_resume_cycles_do_not_leak_rotated_pairs(self, tmp_path):
        """keep-last-K must hold across restarts: once a resuming session
        consumes the crashed run's pairs, its own saves GC them."""
        ckpt_dir = tmp_path / "ckpt"
        crashed = Session(ckpt_dir)
        crashed.save_checkpoint(self._board(5), 5, keep=3)
        crashed.save_checkpoint(self._board(10), 10, keep=3)
        # Fresh process: adopt (marks the old pairs consumed)...
        resumed = Session(ckpt_dir)
        assert resumed.check_states(16, 16).turn == 10
        # ...and its own periodic saves prune the dead pairs.
        resumed.save_checkpoint(self._board(15), 15, keep=3)
        stems = sorted(p.stem for p in ckpt_dir.glob("checkpoint-*.json"))
        assert stems == ["checkpoint-000000000015"], stems

    def test_shared_dir_scan_skips_foreign_pairs(self, tmp_path):
        """A shared checkpoint dir: another controller's shape-mismatched
        pair must neither shadow this controller's own (older-turn)
        rotated pair nor be consumed by its adoption."""
        ckpt_dir = tmp_path / "ckpt"
        foreign = Session(ckpt_dir)  # run A: 32x32 detach at a NEWER turn
        foreign.pause(True, world=np.zeros((32, 32), np.uint8), turn=50)
        mine = Session(ckpt_dir)  # run B: 16x16 periodic pair, then "crash"
        mine.save_checkpoint(self._board(10), 10, rule="B3/S23")

        # Fresh 16x16 process: must find B's turn-10 pair despite A's
        # newer foreign one...
        ck = Session(ckpt_dir).check_states(16, 16, "B3/S23")
        assert ck is not None and ck.turn == 10
        # ...and consuming it must not touch A's pair.
        ck_a = Session(ckpt_dir).check_states(32, 32)
        assert ck_a is not None and ck_a.turn == 50

    def test_wall_clock_cadence_checkpoints(self, tmp_path):
        """checkpoint_every_seconds: latency-spiked dispatches (injected)
        guarantee the clock advances past the cadence between dispatch
        boundaries, so at least one periodic checkpoint lands."""
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.testing.faults import (
            Fault,
            FaultInjectionBackend,
            FaultPlan,
        )

        params = gol.Params(
            turns=20,
            image_width=16,
            image_height=16,
            soup_density=0.3,
            soup_seed=7,
            out_dir=tmp_path,
            superstep=5,
            engine="roll",
            cycle_check=0,
            checkpoint_every_seconds=0.01,
        )
        plan = FaultPlan(Fault(i, "latency", seconds=0.03) for i in range(4))
        backend = FaultInjectionBackend(Backend(params), plan)
        session = Session()
        events: queue.Queue = queue.Queue()
        gol.run(params, events, session=session, backend=backend)
        stream = drain(events)
        assert [e for e in stream if isinstance(e, gol.CheckpointSaved)]
        assert session.check_states(16, 16) is None  # completed => discarded


def _drain_nonblocking(events):
    out = []
    while True:
        try:
            e = events.get_nowait()
        except queue.Empty:
            return out
        if e is None:
            raise AssertionError("unexpected stream end")
        out.append(e)


def test_sharded_detach_and_resume(tmp_path, input_images, golden_images):
    """Resume × sharding: a 'q' detach from a mesh-sharded run parks a
    host checkpoint a fresh sharded run resumes bit-exactly."""
    session = Session()
    params = make_params(
        tmp_path, input_images, turns=10**6, superstep=4, mesh_shape=(2, 4),
        image_width=64, image_height=64,
    )
    events, keys, thread = start_run(params, session)
    collected: list = []
    wait_for_turns(events, 8, collected)
    keys.put("q")
    drain(events)
    thread.join(timeout=30)
    ckpt = session.check_states(64, 64)
    assert ckpt is not None and ckpt.turn >= 8
    # Put it back (check_states consumed it) and resume to turn 100.
    session.pause(True, world=ckpt.world, turn=ckpt.turn)
    params2 = make_params(
        tmp_path, input_images, turns=100, mesh_shape=(2, 4),
        image_width=64, image_height=64,
    )
    ev2, _, t2 = start_run(params2, session)
    final = [e for e in drain(ev2) if isinstance(e, gol.FinalTurnComplete)][0]
    t2.join(timeout=30)
    assert final.completed_turns == 100
    got = (tmp_path / "64x64x100.pgm").read_bytes()
    want = (golden_images / "64x64x100.pgm").read_bytes()
    assert got == want
