"""The chaos matrix (ISSUE 2 tentpole): execution tier × fault kind.

Drives the deterministic fault harness (``testing.faults``) through the
full controller on every execution tier — single device, sharded mesh on
the per-turn ppermute engine, and the sharded adaptive ``pallas-packed``
tier (the one that hosts the in-kernel ICI exchange on TPU meshes; on this
CPU rig the tier policy records its ppermute strip form, the same
controller/backend seam) — and asserts the fault-tolerance contract: every
injected failure ends in either a **bit-identical recovery** against the
fault-free oracle, or a **clean sentinel-terminated abort with a valid
resumable checkpoint** whose resumed run lands back on the oracle board.
Never a hang (the dispatch watchdog + the conftest faulthandler guard
bound every case), never silent corruption (a torn checkpoint write is
detected by its CRC and skipped for an older intact pair).

Marked ``chaos`` (registered in pytest.ini) so the failure-path suite can
be run alone: ``pytest -m chaos``.
"""

import queue
import time
import warnings

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.controller import DispatchTimeout
from distributed_gol_tpu.engine.events import CheckpointSaved, DispatchError
from distributed_gol_tpu.engine.pgm import read_pgm
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.engine.supervisor import GracefulStop, supervise
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)

pytestmark = pytest.mark.chaos

# Each tier: 6 dispatches of `superstep` turns on a seeded soup.  Explicit
# superstep + cycle_check=0 keep the dispatch schedule (= fault-plan
# indices) exact and identical across the faulted run and the oracle.
TIERS = {
    "single": dict(
        engine="roll", mesh_shape=(1, 1), image_width=16, image_height=16,
        superstep=4, turns=24,
    ),
    "sharded-ppermute": dict(
        engine="packed", mesh_shape=(8, 1), image_width=64, image_height=64,
        superstep=5, turns=30,
    ),
    "ici-adaptive": dict(
        engine="pallas-packed", mesh_shape=(2, 1), skip_stable=True,
        image_width=128, image_height=64, superstep=6, turns=36,
    ),
}


def tier_params(tier, out_dir, **kw):
    cfg = dict(TIERS[tier])
    cfg.update(
        soup_density=0.25,
        soup_seed=11,
        out_dir=out_dir,
        cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return gol.Params(**cfg)


def drain(events):
    out = []
    while (e := events.get(timeout=60)) is not None:
        out.append(e)
    return out


def run_ok(params, backend=None, session=None):
    session = session if session is not None else Session()
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, backend=backend)
    return drain(events), session


def run_aborting(params, backend, session, exc=RuntimeError):
    events: queue.Queue = queue.Queue()
    with pytest.raises(exc):
        gol.run(params, events, session=session, backend=backend)
    # The sentinel is guaranteed even on the abort path: this drain
    # terminating (instead of timing out) IS the assertion.
    return drain(events)


@pytest.fixture(scope="module")
def oracle(tmp_path_factory):
    """Fault-free reference run per tier, computed once: (final event,
    final board bytes) — the recovery target every chaos case compares
    against."""
    cache = {}

    def get(tier):
        if tier not in cache:
            out = tmp_path_factory.mktemp(f"oracle-{tier}")
            p = tier_params(tier, out)
            stream, _ = run_ok(p)
            final = [
                e for e in stream if isinstance(e, gol.FinalTurnComplete)
            ][0]
            board = (out / f"{p.final_output_name}.pgm").read_bytes()
            cache[tier] = (final, board)
        return cache[tier]

    return get


def assert_matches_oracle(tier, params, stream, oracle):
    want_final, want_board = oracle(tier)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert sorted(final.alive) == sorted(want_final.alive)
    got = (params.out_dir / f"{params.final_output_name}.pgm").read_bytes()
    assert got == want_board, f"{tier}: final board differs from oracle"


def assert_flight_explains(dirpath, cause: str):
    """The flight-recorder half of the abort contract (ISSUE 4): every
    abort scenario must leave a parseable ``flight-<ts>.json`` whose tail
    record explains the abort cause — and whose embedded metrics snapshot
    is schema-valid."""
    from distributed_gol_tpu.obs.metrics import check_metrics_snapshot

    path = flight_lib.latest_flight_record(dirpath)
    assert path is not None, f"no flight record under {dirpath}"
    doc = flight_lib.load_flight_record(path)  # parses + schema-checks
    assert doc["cause"] == cause
    tail = doc["records"][-1]
    assert tail["kind"] == "abort" and tail["cause"] == cause
    # The ring must show the failure history leading up to the abort, not
    # just the abort itself.
    kinds = {r["kind"] for r in doc["records"]}
    assert "terminal_failure" in kinds
    assert check_metrics_snapshot(doc["metrics"]) == []
    return doc


def assert_no_flight(dirpath):
    """A run that did not die must leave NO flight record — absence is
    the 'nothing went wrong' signal."""
    assert flight_lib.latest_flight_record(dirpath) is None


def resume_and_check(tier, tmp_path, session_dir_or_session, oracle):
    """A fresh controller resumes from the parked checkpoint and must land
    bit-identically on the oracle board."""
    out = tmp_path / "resumed"
    out.mkdir(exist_ok=True)
    params = tier_params(tier, out)
    session = (
        session_dir_or_session
        if isinstance(session_dir_or_session, Session)
        else Session(session_dir_or_session)
    )
    stream, _ = run_ok(params, session=session)
    assert_matches_oracle(tier, params, stream, oracle)


@pytest.mark.parametrize("tier", TIERS)
def test_issue_fault_recovers_bit_identically(tier, tmp_path, oracle):
    params = tier_params(tier, tmp_path)
    backend = FaultInjectionBackend(Backend(params), FaultPlan([Fault(1, "issue")]))
    stream, session = run_ok(params, backend)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True]
    assert_matches_oracle(tier, params, stream, oracle)
    assert session.check_states(params.image_width, params.image_height) is None
    # Recovered (and fault-free) runs leave no postmortem artifact.
    assert_no_flight(tmp_path)
    # ...but the run's own telemetry shows the retry that saved it.
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    assert report.snapshot["counters"]["faults.retries"] == 1


@pytest.mark.parametrize("tier", TIERS)
def test_resolve_fault_recovers_bit_identically(tier, tmp_path, oracle):
    params = tier_params(tier, tmp_path)
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(1, "resolve")])
    )
    stream, session = run_ok(params, backend)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True]
    assert "resolve-time" in errors[0].error
    assert_matches_oracle(tier, params, stream, oracle)
    assert_no_flight(tmp_path)


@pytest.mark.parametrize("tier", TIERS)
def test_burst_aborts_cleanly_and_resumes(tier, tmp_path, oracle):
    """A 2-failure burst defeats the default retry budget: sentinel-
    terminated abort, last good board parked, resume lands on the oracle."""
    params = tier_params(tier, tmp_path / "faulted")
    (tmp_path / "faulted").mkdir()
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(2, "issue"), Fault(3, "issue")])
    )
    session = Session()
    stream = run_aborting(params, backend, session)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[-1].checkpointed
    # In-memory session: the postmortem lands next to the run's out_dir.
    doc = assert_flight_explains(tmp_path / "faulted", "RuntimeError")
    assert doc["metrics"]["counters"]["faults.retries"] == 1
    ckpt = session.check_states(params.image_width, params.image_height)
    assert ckpt is not None and 0 < ckpt.turn < params.turns
    session.pause(True, world=ckpt.world, turn=ckpt.turn)  # re-park (consumed)
    resume_and_check(tier, tmp_path, session, oracle)


@pytest.mark.parametrize("tier", TIERS)
def test_hang_is_bounded_by_the_watchdog(tier, tmp_path, oracle):
    """A dispatch that never resolves must abort via DispatchTimeout within
    the deadline — sentinel, parked checkpoint, resumable — not wedge."""
    params = tier_params(
        tier, tmp_path / "faulted", dispatch_deadline_seconds=1.0
    )
    (tmp_path / "faulted").mkdir()
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(1, "hang", seconds=90.0)])
    )
    session = Session()
    t0 = time.monotonic()
    try:
        stream = run_aborting(params, backend, session, exc=DispatchTimeout)
        elapsed = time.monotonic() - t0
        # Bounded abort: deadline + park + slack, nowhere near the 90 s
        # hang.  The margin is rig-contention-proof (round-6 audit): the
        # hang is a sleep, so it does not slow under load, while the
        # abort path (deadline 1 s + a park) has 44 s of slack before
        # this assert could confuse the two — the old 25 s hang / 15 s
        # bound left only 10 s on a 1-core rig running both suites.
        # release_hangs() in the finally frees the sleeper immediately,
        # so the longer plan costs no wall-clock.
        assert elapsed < 45, f"watchdog abort took {elapsed:.1f}s"
        errors = [e for e in stream if isinstance(e, DispatchError)]
        assert len(errors) == 1 and not errors[0].will_retry  # never retried
        assert errors[0].checkpointed
        doc = assert_flight_explains(tmp_path / "faulted", "DispatchTimeout")
        # The watchdog transition made it into the ring AND the counters.
        assert "watchdog_fire" in {r["kind"] for r in doc["records"]}
        assert doc["metrics"]["counters"]["faults.watchdog_fires"] >= 1
        assert doc["metrics"]["counters"]["faults.watchdog_arms"] >= 1
    finally:
        backend.release_hangs()
    ckpt = session.check_states(params.image_width, params.image_height)
    assert ckpt is not None and ckpt.turn == TIERS[tier]["superstep"]
    session.pause(True, world=ckpt.world, turn=ckpt.turn)
    resume_and_check(tier, tmp_path, session, oracle)


@pytest.mark.parametrize("tier", TIERS)
def test_torn_checkpoint_skipped_for_older_intact_pair(tier, tmp_path, oracle):
    """Periodic checkpoints + a mid-run abort leave rotated pairs on disk;
    tearing the newest pairs (truncated world files — the crash-mid-write
    artifact) must make a fresh process fall back to the newest INTACT
    pair, warn once, and still land on the oracle board."""
    ckpt_dir = tmp_path / "ckpt"
    out = tmp_path / "faulted"
    out.mkdir()
    superstep = TIERS[tier]["superstep"]
    params = tier_params(tier, out, checkpoint_every_turns=superstep)
    backend = FaultInjectionBackend(
        Backend(params), FaultPlan([Fault(2, "issue"), Fault(3, "issue")])
    )
    session = Session(ckpt_dir)
    stream = run_aborting(params, backend, session)
    assert [e for e in stream if isinstance(e, CheckpointSaved)]
    # Durable session: the postmortem lands NEXT TO the checkpoints, and
    # its ring shows the checkpoint commits that preceded the abort.
    doc = assert_flight_explains(ckpt_dir, "RuntimeError")
    assert "checkpoint" in {r["kind"] for r in doc["records"]}

    # Two dispatches completed: rotated pairs at turns s and 2s, plus the
    # terminal park (legacy stem) at 2s.  Tear the two newest worlds.
    legacy = ckpt_dir / "checkpoint.pgm"
    newest = ckpt_dir / f"checkpoint-{2 * superstep:012d}.pgm"
    for path in (legacy, newest):
        assert path.exists(), f"expected checkpoint world {path}"
        path.write_bytes(path.read_bytes()[: max(8, path.stat().st_size // 2)])
    intact = ckpt_dir / f"checkpoint-{superstep:012d}.pgm"
    assert intact.exists()

    # Fresh process analog: a new durable Session must skip the torn pairs
    # (one-time warnings) and resume from turn s — never crash, never
    # silently resume corrupt state.
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        resume_and_check(tier, tmp_path, Session(ckpt_dir), oracle)
    torn = [w for w in caught if issubclass(w.category, RuntimeWarning)]
    assert torn, "torn checkpoints should be warned about"


def test_torn_sidecar_and_torn_world_degrade_to_no_checkpoint(tmp_path):
    """Single-pair corruption (no rotation to fall back to): a truncated
    sidecar or a truncated world file means 'no checkpoint' plus a one-time
    warning — a fresh run starts from turn 0 instead of raising out of
    resume negotiation."""
    for kind in ("sidecar", "world"):
        ckpt_dir = tmp_path / f"ckpt-{kind}"
        s1 = Session(ckpt_dir)
        s1.pause(True, world=np.zeros((16, 16), np.uint8), turn=9, rule="B3/S23")
        victim = ckpt_dir / ("checkpoint.json" if kind == "sidecar" else "checkpoint.pgm")
        victim.write_bytes(victim.read_bytes()[:10])

        s2 = Session(ckpt_dir)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            assert s2.check_states(16, 16, "B3/S23") is None
            assert s2.check_states(16, 16, "B3/S23") is None  # and again
        warned = [w for w in caught if issubclass(w.category, RuntimeWarning)]
        assert len(warned) == 1, f"{kind}: want exactly one warning, got {warned}"

        # A faulted-but-checkpointless run still completes from turn 0.
        out = tmp_path / f"out-{kind}"
        out.mkdir()
        params = tier_params("single", out)
        events: queue.Queue = queue.Queue()
        gol.run(params, events, session=s2)
        final = [e for e in drain(events) if isinstance(e, gol.FinalTurnComplete)]
        assert final and final[0].completed_turns == params.turns


# -- ISSUE 5: the self-healing runtime rows -----------------------------------
#
# The three legs of the resilience layer, hermetically: (1) the supervisor
# survives a post-retry TERMINAL fault with a bit-identical final board,
# (2) a graceful stop (the SIGTERM latch) mid-run yields a resumable
# emergency checkpoint whose resumed run equals the oracle, (3) an
# injected `corrupt` fault is caught by the SDC sentinel within its
# cadence and rolled back to oracle-identical state — plus the ladder-
# exhaustion degradation to PR 2's clean abort with the restart history
# in the flight tail.  Supervisor-OFF preservation is the rest of this
# file: every pre-existing row runs with restart_limit=0 (the default)
# and still expects the PR-2 terminal-but-clean contract.


def _fault_first_attempt(plan: FaultPlan):
    """A supervisor backend factory: attempt 0 gets the fault harness,
    every rebuilt attempt gets a clean backend of the same params."""

    def factory(params, attempt):
        backend = Backend(params)
        return FaultInjectionBackend(backend, plan) if attempt == 0 else backend

    return factory


def run_supervised(params, backend_factory, session=None):
    session = session if session is not None else Session()
    events: queue.Queue = queue.Queue()
    sup = supervise(
        params, events, session=session, backend_factory=backend_factory
    )
    return drain(events), sup, session


@pytest.mark.parametrize("tier", TIERS)
def test_supervisor_survives_terminal_burst(tier, tmp_path, oracle):
    """Tentpole leg 1: a 2-failure burst defeats the retry budget — a
    TERMINAL failure under PR 2 — but the supervisor restores the parked
    checkpoint, rebuilds the backend, resumes, and the final board is
    bit-identical to the fault-free oracle.  A recovered run writes no
    flight record; its terminal MetricsReport documents the restart."""
    s = TIERS[tier]["superstep"]
    params = tier_params(
        tier, tmp_path, checkpoint_every_turns=s, restart_limit=2
    )
    stream, sup, session = run_supervised(
        params,
        _fault_first_attempt(FaultPlan([Fault(2, "issue"), Fault(3, "issue")])),
    )
    errors = [e for e in stream if isinstance(e, DispatchError)]
    # The retry and the terminal failure are still announced; the stream
    # then CONTINUES through the recovery instead of ending.
    assert [e.will_retry for e in errors] == [True, False]
    assert_matches_oracle(tier, params, stream, oracle)
    assert_no_flight(tmp_path)
    assert len(sup.history) == 1
    assert sup.history[0]["cause"] == "RuntimeError"
    assert sup.recovery_times(), "restart left no measurable recovery gap"
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["supervisor.restarts"] == 1
    assert counters["faults.retries"] == 1
    # Nothing left parked: the recovered run completed and consumed its
    # own rollback state.
    assert session.check_states(params.image_width, params.image_height) is None


@pytest.mark.parametrize("tier", TIERS)
def test_corrupt_is_detected_and_rolled_back(tier, tmp_path, oracle):
    """Tentpole leg 3: seeded bit-flips at the resolve seam (the `corrupt`
    fault kind) are silent — no exception — so only the SDC sentinel can
    see them.  It must catch the corruption within sdc_check_every_turns
    turns (here: at the corrupted dispatch's own boundary), raise
    CorruptionDetected WITHOUT checkpointing the corrupt board, and the
    supervisor must roll back to the last clean checkpoint and land
    bit-identically on the oracle."""
    s = TIERS[tier]["superstep"]
    params = tier_params(
        tier,
        tmp_path,
        checkpoint_every_turns=s,
        sdc_check_every_turns=s,
        restart_limit=2,
    )
    stream, sup, _ = run_supervised(
        params, _fault_first_attempt(FaultPlan([Fault(2, "corrupt", cells=3)]))
    )
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert any("SDC sentinel" in e.error for e in errors)
    assert not any(e.checkpointed for e in errors)  # corrupt board never parked
    assert_matches_oracle(tier, params, stream, oracle)
    assert_no_flight(tmp_path)
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["sdc.mismatches"] == 1
    assert counters["sdc.checks"] >= 2  # post-rollback checks pass again
    assert counters["supervisor.restarts"] == 1
    # Caught at the corrupted dispatch's own boundary, rolled back exactly
    # one dispatch (the corruption struck dispatch 2 -> turn 3s; the last
    # clean checkpoint is turn 2s).
    assert sup.history[0]["cause"] == "CorruptionDetected"
    assert sup.history[0]["from_turn"] == 3 * s
    assert sup.history[0]["resume_turn"] == 2 * s
    assert counters["supervisor.rollback_turns"] == s


def test_restart_exhaustion_degrades_to_clean_abort(tmp_path):
    """The restart-ladder bound: a backend that keeps producing terminal
    failures exhausts restart_limit and the run degrades to PR 2's
    sentinel abort — with every restart documented in the flight record
    leading up to the abort tail."""
    params = tier_params(
        "single", tmp_path / "faulted", checkpoint_every_turns=4,
        restart_limit=2,
    )
    (tmp_path / "faulted").mkdir()
    # Terminal on the very first dispatch of EVERY attempt: no attempt
    # makes progress, so the budget must genuinely exhaust.
    plan = FaultPlan([Fault(0, "issue"), Fault(1, "issue")])

    def always_faulty(p, attempt):
        return FaultInjectionBackend(Backend(p), plan)

    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError):
        supervise(params, events, session=session, backend_factory=always_faulty)
    stream = drain(events)  # sentinel still guaranteed on the abort path
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert sum(1 for e in errors if not e.will_retry) == 3  # one per attempt
    doc = assert_flight_explains(tmp_path / "faulted", "RuntimeError")
    restarts = [r for r in doc["records"] if r["kind"] == "restart"]
    assert [r["attempt"] for r in restarts] == [1, 2]
    assert "supervisor_exhausted" in {r["kind"] for r in doc["records"]}
    assert doc["metrics"]["counters"]["supervisor.restarts"] == 2


@pytest.mark.parametrize("tier", TIERS)
def test_preempt_mid_run_yields_resumable_checkpoint(tier, tmp_path, oracle):
    """Tentpole leg 2: a graceful stop (what the SIGTERM handler latches)
    observed mid-run forces an out-of-cadence emergency checkpoint and
    exits paused-and-resumable; a fresh controller on the same session
    completes the run bit-identically to the never-preempted oracle.
    Latency faults pace the run so the stop deterministically lands
    before completion."""
    ckpt_dir = tmp_path / "ckpt"
    out = tmp_path / "preempted"
    out.mkdir()
    params = tier_params(tier, out)
    superstep = TIERS[tier]["superstep"]
    # 0.3 s per dispatch from dispatch 1 on: the stop (sent on the first
    # TurnComplete) has seconds of margin before the run could finish.
    backend = FaultInjectionBackend(
        Backend(params),
        FaultPlan([Fault(i, "latency", seconds=0.3) for i in range(1, 8)]),
    )
    stop = GracefulStop()
    session = Session(ckpt_dir)
    events: queue.Queue = queue.Queue()
    thread = gol.start(params, events, session=session, backend=backend, stop=stop)
    seen = []
    while (e := events.get(timeout=60)) is not None:
        seen.append(e)
        if isinstance(e, gol.TurnComplete) and not stop.requested:
            stop.request()
    thread.join(timeout=60)
    assert not thread.is_alive()

    final = [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.alive == ()  # paused exit, not a completion
    assert superstep <= final.completed_turns < params.turns
    saved = [e for e in seen if isinstance(e, CheckpointSaved)]
    assert saved and saved[-1].completed_turns == final.completed_turns
    report = [e for e in seen if isinstance(e, gol.MetricsReport)][0]
    assert report.snapshot["counters"]["preempt.signals"] == 1
    # A preempted run is a CLEAN exit: no postmortem artifact anywhere.
    assert_no_flight(out)
    assert_no_flight(ckpt_dir)

    # Fresh-process analog: a new durable Session adopts the emergency
    # checkpoint and the resumed run lands exactly on the oracle board.
    resume_and_check(tier, tmp_path, Session(ckpt_dir), oracle)


def test_stop_while_paused_preempts_at_the_frozen_turn(tmp_path, oracle):
    """A graceful stop observed while the run is PAUSED must preempt at
    the exact turn the user froze — not one dispatch later.  The paused
    keys loop returns with the stop latched and the call site preempts
    immediately; a fall-through would compute one more superstep and
    park the emergency checkpoint past the frozen state."""
    from distributed_gol_tpu.engine.events import State, StateChange

    ckpt_dir = tmp_path / "ckpt"
    out = tmp_path / "preempted"
    out.mkdir()
    params = tier_params("single", out)
    # 0.3 s per dispatch: the 'p' sent on the first TurnComplete lands
    # at a boundary with most of the run still ahead.
    backend = FaultInjectionBackend(
        Backend(params),
        FaultPlan([Fault(i, "latency", seconds=0.3) for i in range(1, 8)]),
    )
    stop = GracefulStop()
    keys: queue.Queue = queue.Queue()
    session = Session(ckpt_dir)
    events: queue.Queue = queue.Queue()
    thread = gol.start(
        params, events, keys, session=session, backend=backend, stop=stop
    )
    seen = []
    paused_turn = None
    pause_sent = False
    while (e := events.get(timeout=60)) is not None:
        seen.append(e)
        if isinstance(e, gol.TurnComplete) and not pause_sent:
            pause_sent = True
            keys.put("p")
        if (
            isinstance(e, StateChange)
            and e.new_state is State.PAUSED
            and paused_turn is None
        ):
            paused_turn = e.completed_turns
            stop.request()
    thread.join(timeout=60)
    assert not thread.is_alive()

    assert paused_turn is not None and 0 < paused_turn < params.turns
    final = [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.alive == ()  # paused exit, not a completion
    # The whole point: the run froze at paused_turn and stayed there.
    assert final.completed_turns == paused_turn
    saved = [e for e in seen if isinstance(e, CheckpointSaved)]
    assert saved and saved[-1].completed_turns == paused_turn
    report = [e for e in seen if isinstance(e, gol.MetricsReport)][0]
    assert report.snapshot["counters"]["preempt.signals"] == 1
    assert_no_flight(out)
    assert_no_flight(ckpt_dir)
    resume_and_check("single", tmp_path, Session(ckpt_dir), oracle)


def test_wallclock_checkpoint_is_verified_before_park(tmp_path, oracle):
    """Verify-before-park: with the sentinel armed, a wall-clock
    checkpoint cadence (which cannot be ordered against the SDC turn
    cadence at validation time) must never persist an unverified board.
    The sentinel's own cadence here is far coarser than the run, so the
    ONLY checks that can catch the corruption are the ones forced at
    parking boundaries — without them the seconds cadence checkpoints
    the corrupt board and the supervisor 'recovers' into corruption."""
    s = TIERS["single"]["superstep"]
    params = tier_params(
        "single",
        tmp_path,
        checkpoint_every_seconds=1e-6,  # every boundary parks
        sdc_check_every_turns=10**6,  # cadence alone would never check
        restart_limit=2,
    )
    stream, sup, _ = run_supervised(
        params, _fault_first_attempt(FaultPlan([Fault(2, "corrupt", cells=3)]))
    )
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert any("SDC sentinel" in e.error for e in errors)
    assert not any(e.checkpointed for e in errors)  # corrupt board never parked
    assert_matches_oracle("single", params, stream, oracle)
    assert_no_flight(tmp_path)
    report = [e for e in stream if isinstance(e, gol.MetricsReport)][0]
    counters = report.snapshot["counters"]
    assert counters["sdc.mismatches"] == 1
    assert counters["supervisor.restarts"] == 1
    # Caught at the corrupted dispatch's own parking boundary: rollback is
    # exactly one dispatch, to the verified checkpoint before it.
    assert sup.history[0]["cause"] == "CorruptionDetected"
    assert sup.history[0]["from_turn"] == 3 * s
    assert sup.history[0]["resume_turn"] == 2 * s
